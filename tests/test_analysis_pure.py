"""Trace-time verifier: the pure-Python half (docs/analysis.md).

Drives the checker registry, the report/rendering layer, the jaxpr
walker (with duck-typed fake jaxprs), and the ``MPI4JAX_TPU_ANALYZE``
mode plumbing — all loaded under a private package name
(``_load_isolated``, mirroring tests/test_algos.py) so these tests run
even where the installed JAX is below the package's hard floor and
``import mpi4jax_tpu`` refuses.  One positive (finding fired: code +
message asserted) and one negative (clean graph: no finding of that
code) per graph-level checker; the traced integration half — the same
hazards driven through ``mpx.analyze`` and the env-mode dispatch path —
lives in tests/test_analysis.py.
"""

import importlib
import os
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_analysis_iso"


def _load_isolated():
    """Load analysis/* + utils/config.py under a private package name,
    bypassing ``mpi4jax_tpu/__init__.py`` (whose JAX-floor check refuses
    to import on old JAX) while preserving package context for the
    relative imports."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "parallel", "ops"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._fusion", "analysis.report",
                "analysis.graph", "analysis.checkers", "analysis.walker",
                "analysis.dataflow", "analysis.hazards",
                "analysis.hook", "analysis.schedule", "analysis.matcher",
                "analysis.progress", "analysis.costmodel", "analysis.cost",
                "parallel.rankspec"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
report = sys.modules[f"{_ISO_NAME}.analysis.report"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
walker = sys.modules[f"{_ISO_NAME}.analysis.walker"]
hook = sys.modules[f"{_ISO_NAME}.analysis.hook"]
config = sys.modules[f"{_ISO_NAME}.utils.config"]
rankspec = sys.modules[f"{_ISO_NAME}.parallel.rankspec"]

E = graph.CollectiveEvent
G = graph.CollectiveGraph


def codes_of(g):
    return [f.code for f in checkers.run_checkers(g)]


# ---------------------------------------------------------------------------
# registry / catalog coverage
# ---------------------------------------------------------------------------


def test_catalog_is_fully_owned():
    # every code is emitted by a graph checker, except MPX108 (the jaxpr
    # walker owns it: control-flow structure is invisible to the event
    # stream), the cross-rank codes (the schedule matcher and the
    # progress checker own those — analysis/matcher.py + progress.py),
    # and MPX129 (owned by the tagged raise site in aot/invalidation.py:
    # a stale pinned call refuses BEFORE dispatch, so no event stream
    # can ever witness one — mpx.analyze converts the raise)
    matcher = sys.modules[f"{_ISO_NAME}.analysis.matcher"]
    progress = sys.modules[f"{_ISO_NAME}.analysis.progress"]
    cost = sys.modules[f"{_ISO_NAME}.analysis.cost"]
    crossrank_owned = set(matcher.CROSSRANK_CODES) | set(
        progress.CROSSRANK_CODES)
    # MPX131-135 are owned by the cost-pass critic (analysis/cost.py):
    # quantified advisories over the timed simulation, never emitted by
    # a graph checker
    cost_owned = set(cost.COST_CODES)
    raise_site_owned = {"MPX129"}
    # MPX141/142 are owned by the dataflow taint pass (analysis/
    # dataflow.py) — jaxpr-level like MPX108; the graph-side hazard
    # checkers (MPX139/140, analysis/hazards.py) register normally
    jaxpr_owned = {"MPX108"} | set(report.HAZARD_JAXPR_CODES)
    assert (checkers.registered_codes() | jaxpr_owned | crossrank_owned
            | cost_owned | raise_site_owned == set(report.CODES))
    # the registries never claim the same code
    assert not crossrank_owned & checkers.registered_codes()
    assert not cost_owned & (crossrank_owned | checkers.registered_codes())


def test_codes_have_severity_and_docs():
    for code, info in report.CODES.items():
        assert info.severity in (report.ERROR, report.ADVISORY)
        assert info.title and info.doc


def test_analysis_doc_lists_every_code():
    doc = (REPO / "docs" / "analysis.md").read_text()
    missing = [c for c in report.CODES if c not in doc]
    assert not missing, f"codes absent from docs/analysis.md: {missing}"


# ---------------------------------------------------------------------------
# MPX101 / MPX102 / MPX106 / MPX110 — p2p matching replay
# ---------------------------------------------------------------------------


def test_mpx101_unmatched_send_fires():
    g = G(events=[E(0, "send", comm_uid=1, tag=3, dtype="float32",
                    shape=(4,))])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX101"
    assert "never" in f.message and "FIFO" in f.message
    assert "matching recv" in f.suggestion


def test_mpx102_recv_without_send_fires():
    g = G(events=[E(0, "recv", comm_uid=1, tag=0)])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX102"
    assert "no matching send" in f.message


def test_matched_pair_is_clean():
    g = G(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="float32", shape=(4,)),
        E(1, "recv", comm_uid=1, tag=0, dtype="float32", shape=(4,)),
    ])
    assert codes_of(g) == []


def test_eager_recv_is_not_replayed():
    # eager p2p uses deferred pairing: the send never enters dispatch, so
    # a lone eager recv event must NOT fire MPX102
    g = G(events=[E(0, "recv", comm_uid=1, tag=0, eager=True)])
    assert codes_of(g) == []


def test_mpx106_signature_mismatch_fires_and_clean():
    g = G(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="float32", shape=(4,)),
        E(1, "recv", comm_uid=1, tag=0, dtype="int32", shape=(4,)),
    ])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX106"
    assert "type-signature" in f.message
    # same element count, different shape: allowed (output typed by
    # template)
    g = G(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="float32", shape=(1, 4)),
        E(1, "recv", comm_uid=1, tag=0, dtype="float32", shape=(4, 1)),
    ])
    assert codes_of(g) == []


def test_mpx110_ambiguous_fifo_fires_and_clean():
    two_sends = [
        E(0, "send", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(1, "send", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(2, "recv", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(3, "recv", comm_uid=1, tag=0, dtype="f", shape=(1,)),
    ]
    codes = codes_of(G(events=two_sends))
    assert codes == ["MPX110"]
    # distinct tags: unambiguous
    g = G(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(1, "send", comm_uid=1, tag=1, dtype="f", shape=(1,)),
        E(2, "recv", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(3, "recv", comm_uid=1, tag=1, dtype="f", shape=(1,)),
    ])
    assert codes_of(g) == []


# ---------------------------------------------------------------------------
# MPX103 / MPX104 — structural statics (graph events + tagged raise sites)
# ---------------------------------------------------------------------------


def test_mpx103_bare_int_event_and_raise_site():
    g = G(events=[E(0, "sendrecv", comm_uid=1, tag=0,
                    extra={"bare_int_routing": True})])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX103" and "bare int" in f.message
    # the live raise site carries the same code
    with pytest.raises(TypeError, match=r"ambiguous under SPMD.*\[MPX103\]") as ei:
        rankspec.normalize_dest(1, 4, what="send")
    assert ei.value.mpx_code == "MPX103"


def test_mpx104_traced_structure_event():
    g = G(events=[E(0, "bcast", comm_uid=1,
                    extra={"traced_structure": "root"})])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX104" and "tracer" in f.message
    assert codes_of(G(events=[E(0, "bcast", comm_uid=1, root=0,
                                min_size=4)])) == []


# ---------------------------------------------------------------------------
# MPX105 — root range
# ---------------------------------------------------------------------------


def test_mpx105_root_out_of_range_fires_and_clean():
    g = G(events=[E(0, "bcast", comm_uid=1, root=9, min_size=8)])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX105"
    assert "root 9 out of range" in f.message
    assert "[0, 8)" in f.suggestion
    assert codes_of(G(events=[E(0, "bcast", comm_uid=1, root=7,
                                min_size=8)])) == []
    # split comms name the smallest group
    g = G(events=[E(0, "bcast", comm_uid=1, root=3, min_size=3, split=True)])
    (f,) = checkers.run_checkers(g)
    assert "smallest group" in f.message


# ---------------------------------------------------------------------------
# MPX107 — token discipline
# ---------------------------------------------------------------------------


def test_mpx107_forked_token_fires():
    g = G(events=[
        E(0, "allreduce", comm_uid=1, token_in=100, token_out=101),
        E(1, "allreduce", comm_uid=1, token_in=100, token_out=102),
    ])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX107"
    assert "never" in f.message and "older token" in f.message


def test_mpx107_clean_chains():
    # linear chain: final token legitimately unconsumed
    g = G(events=[
        E(0, "allreduce", comm_uid=1, token_in=100, token_out=101),
        E(1, "bcast", comm_uid=1, token_in=101, token_out=102),
    ])
    assert codes_of(g) == []
    # tokenless program
    g = G(events=[E(0, "allreduce", comm_uid=1),
                  E(1, "allreduce", comm_uid=1)])
    assert codes_of(g) == []
    # notoken passthrough (produce returns the same token)
    g = G(events=[
        E(0, "allreduce", comm_uid=1, token_in=100, token_out=100),
        E(1, "allreduce", comm_uid=1, token_in=100, token_out=100),
    ])
    assert codes_of(g) == []
    # independent chains on DIFFERENT comms never interact
    g = G(events=[
        E(0, "allreduce", comm_uid=1, token_in=100, token_out=101),
        E(1, "allreduce", comm_uid=2, token_in=200, token_out=201),
    ])
    assert codes_of(g) == []


# ---------------------------------------------------------------------------
# MPX109 — crossover proximity advisory
# ---------------------------------------------------------------------------


def _algo_graph(payload, algo="butterfly", mode="auto", k=8,
                crossover=1 << 20):
    return G(
        events=[E(0, "allreduce", comm_uid=1, comm_size=k,
                  payload_bytes=payload, algo=algo)],
        meta={"collective_algo": mode, "ring_crossover_bytes": crossover},
    )


def test_mpx109_near_crossover_fires():
    (f,) = checkers.run_checkers(_algo_graph(1 << 20))
    assert f.code == "MPX109"
    assert "within 2x" in f.message
    assert "MPI4JAX_TPU_COLLECTIVE_ALGO" in f.suggestion
    # boundary semantics: [crossover/2, crossover*2)
    assert codes_of(_algo_graph((1 << 19))) == ["MPX109"]
    assert codes_of(_algo_graph((1 << 21) - 1)) == ["MPX109"]


def test_mpx109_negative_cases():
    assert codes_of(_algo_graph(1 << 10)) == []          # far below
    assert codes_of(_algo_graph(1 << 22)) == []          # far above
    assert codes_of(_algo_graph(1 << 20, mode="ring")) == []   # forced
    assert codes_of(_algo_graph(1 << 20, algo="native")) == []  # native HLO
    assert codes_of(_algo_graph(1 << 20, k=2)) == []     # below ring min
    assert checkers.RING_MIN_GROUP == 4  # mirrored from ops/_algos.py


# ---------------------------------------------------------------------------
# MPX113 — flat algorithm on a multi-host comm
# ---------------------------------------------------------------------------


def _hier_graph(payload=1 << 22, algo="ring", hosts=2, k=8,
                op="allreduce", crossover=1 << 20, mode="ring"):
    return G(
        events=[E(0, op, comm_uid=1, comm_size=k, payload_bytes=payload,
                  algo=algo, hosts=hosts)],
        meta={"collective_algo": mode, "ring_crossover_bytes": crossover},
    )


def test_mpx113_flat_over_dcn_fires():
    (f,) = checkers.run_checkers(_hier_graph())
    assert f.code == "MPX113"
    assert f.severity == "advisory"
    assert "2 hosts" in f.message and "'ring'" in f.message
    assert "DCN" in f.message
    assert "MPI4JAX_TPU_COLLECTIVE_ALGO=hier" in f.suggestion
    # a forced butterfly on a multi-host comm fires too, and the payload
    # + topology that triggered it are in the message
    (f2,) = checkers.run_checkers(_hier_graph(algo="butterfly",
                                              payload=1 << 21))
    assert f2.code == "MPX113" and f"{1 << 21} B" in f2.message
    # reduce_scatter and bcast are in the algorithm family
    (f3,) = checkers.run_checkers(_hier_graph(op="reduce_scatter"))
    assert f3.code == "MPX113"


def test_mpx113_negative_cases():
    # the hierarchical lowering actually ran: nothing to advise
    assert codes_of(_hier_graph(algo="hier", mode="hier")) == []
    # single host (or no derivable topology -> hosts is None): flat is right
    assert codes_of(_hier_graph(hosts=1)) == []
    assert codes_of(_hier_graph(hosts=None)) == []
    # below the ring crossover the flat butterfly IS the right choice
    # (MPX109 may still advise about crossover proximity — not this rule)
    assert "MPX113" not in codes_of(_hier_graph(payload=(1 << 20) - 1,
                                                algo="butterfly",
                                                mode="auto"))
    # one rank per host: hier degenerates to flat, nothing to gain
    assert codes_of(_hier_graph(hosts=8, k=8)) == []
    # native HLO is XLA-scheduled; not ours to advise on
    assert codes_of(_hier_graph(algo="native")) == []
    # non-algorithm ops never fire
    assert codes_of(_hier_graph(op="scan")) == []


# ---------------------------------------------------------------------------
# MPX111 — adjacent fusable collectives not fused
# ---------------------------------------------------------------------------

_FUSION_META = {"fusion": "off", "fusion_bucket_bytes": 1 << 20,
                "collective_algo": "auto", "ring_crossover_bytes": 1 << 20}


def _adjacent(op="allreduce", n=2, reduction="sum", payload=64, **kw):
    return [E(i, op, comm_uid=1, reduction=reduction,
              payload_bytes=payload, **kw) for i in range(n)]


def test_mpx111_adjacent_unfused_fires():
    g = G(events=_adjacent(n=3), meta=dict(_FUSION_META))
    (f,) = [x for x in checkers.run_checkers(g) if x.code == "MPX111"]
    assert f.severity == "advisory"
    assert "3 adjacent allreduce" in f.message
    assert "MPI4JAX_TPU_FUSION=auto" in f.suggestion
    assert f.index == 0  # anchored at the run's first event


def test_mpx111_mixed_dtypes_still_bucket():
    # dtype segregation happens inside the flush, so a mixed-dtype run is
    # still one fusion opportunity
    evs = [E(0, "allreduce", comm_uid=1, reduction="sum", payload_bytes=64,
             dtype="float32"),
           E(1, "allreduce", comm_uid=1, reduction="sum", payload_bytes=64,
             dtype="int32")]
    g = G(events=evs, meta=dict(_FUSION_META))
    assert [x.code for x in checkers.run_checkers(g)] == ["MPX111"]


def test_mpx111_negative_cases():
    # fusion already on
    g = G(events=_adjacent(), meta={**_FUSION_META, "fusion": "auto"})
    assert codes_of(g) == []
    # no fusion meta at all (hand-built graph testing another rule)
    assert codes_of(G(events=_adjacent())) == []
    # different reductions never bucket
    evs = _adjacent() + [E(2, "allreduce", comm_uid=1, reduction="max",
                           payload_bytes=64)]
    evs[2].index = 2
    g = G(events=[evs[0], evs[2]], meta=dict(_FUSION_META))
    assert codes_of(g) == []
    # an intervening op breaks adjacency
    evs = [E(0, "allreduce", comm_uid=1, reduction="sum", payload_bytes=64),
           E(1, "barrier", comm_uid=1),
           E(2, "allreduce", comm_uid=1, reduction="sum", payload_bytes=64)]
    assert codes_of(G(events=evs, meta=dict(_FUSION_META))) == []
    # members above the bucket cap don't bucket
    g = G(events=_adjacent(payload=(1 << 20) + 1), meta=dict(_FUSION_META))
    assert codes_of(g) == []
    # eager dispatches compile one program per op: nothing to fuse
    g = G(events=_adjacent(eager=True), meta=dict(_FUSION_META))
    assert codes_of(g) == []
    # different roots never bucket (bcast)
    evs = [E(0, "bcast", comm_uid=1, root=0, payload_bytes=64),
           E(1, "bcast", comm_uid=1, root=1, payload_bytes=64)]
    assert codes_of(G(events=evs, meta=dict(_FUSION_META))) == []
    # same-root bcast run fires
    evs = [E(0, "bcast", comm_uid=1, root=0, payload_bytes=64),
           E(1, "bcast", comm_uid=1, root=0, payload_bytes=64)]
    assert codes_of(G(events=evs, meta=dict(_FUSION_META))) == ["MPX111"]
    # callable reductions never defer (ops/allreduce.py gates on enum
    # Ops), so advising fusion for them would be wrong
    g = G(events=_adjacent(reduction="my_combiner"),
          meta=dict(_FUSION_META))
    assert codes_of(g) == []
    assert checkers.ENUM_REDUCTIONS == tuple(
        o for o in ("sum", "prod", "min", "max", "land", "lor", "lxor",
                    "band", "bor", "bxor"))


def test_fusable_ops_mirror():
    # the checker's literal mirror must match the deferral layer's list
    fusion = sys.modules[f"{_ISO_NAME}.ops._fusion"]
    assert checkers.FUSABLE_OPS == fusion.FUSABLE_OPS


def test_config_snapshot_records_fusion():
    snap = hook.config_snapshot()
    assert snap["fusion"] in config.FUSION_MODES
    assert snap["fusion_bucket_bytes"] == config.fusion_bucket_bytes()
    assert snap["alltoall_crossover_bytes"] == \
        config.alltoall_crossover_bytes()


# ---------------------------------------------------------------------------
# MPX137 — flat alltoall on a multi-host comm (the MPX113 analog)
# ---------------------------------------------------------------------------


def _a2a_meta(crossover=1024):
    return {"alltoall_crossover_bytes": crossover}


def test_mpx137_flat_multihost_alltoall_fires():
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=4096, algo="native")],
          meta=_a2a_meta())
    found = [f for f in checkers.run_checkers(g) if f.code == "MPX137"]
    assert len(found) == 1
    f = found[0]
    assert f.severity == "advisory"
    assert "2 hosts" in f.message and "4x the DCN message count" in f.message
    assert "hier" in f.suggestion


def test_mpx137_async_start_counts_like_the_blocking_op():
    g = G(events=[E(0, "alltoall_start", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=4096, algo="pairwise", span=7)],
          meta=_a2a_meta())
    assert "MPX137" in [f.code for f in checkers.run_checkers(g)]


def test_mpx137_cites_measured_crossover():
    # a calibrated file's measured value replaces the static one as the
    # threshold AND in the text (the MPX113 contract, mirrored)
    meta = {"alltoall_crossover_bytes": 1 << 20,
            "measured_alltoall_crossover_bytes": 1024,
            "tuned_stamp": "abc123def456"}
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=4096, algo="native")], meta=meta)
    (f,) = [x for x in checkers.run_checkers(g) if x.code == "MPX137"]
    assert "measured alltoall crossover" in f.message
    assert "tuned@abc123def456" in f.message
    assert "1024 B" in f.message


def test_mpx137_negatives():
    # hier selected: nothing to advise
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=4096, algo="hier", hier=(2, 4))],
          meta=_a2a_meta())
    assert "MPX137" not in codes_of(g)
    # below the crossover: the flat exchange is the right call
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=512, algo="native")],
          meta=_a2a_meta())
    assert "MPX137" not in codes_of(g)
    # no hosts annotation (no plan was derivable): flat is the only option
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8,
                    payload_bytes=4096, algo="native")],
          meta=_a2a_meta())
    assert "MPX137" not in codes_of(g)
    # one rank per host: the hierarchy degenerates — nothing to advise
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=2, hosts=2,
                    payload_bytes=4096, algo="native")],
          meta=_a2a_meta())
    assert "MPX137" not in codes_of(g)
    # hand-built graph without the crossover meta: testing other rules
    g = G(events=[E(0, "alltoall", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=4096, algo="native")])
    assert "MPX137" not in codes_of(g)


# ---------------------------------------------------------------------------
# MPX112 — async start/wait pairing
# ---------------------------------------------------------------------------


def test_mpx112_unwaited_start_fires():
    g = G(events=[E(0, "allreduce_start", comm_uid=1, span=11)])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX112" and f.severity == "error"
    assert "never waited" in f.message
    assert "allreduce_wait" in f.suggestion


def test_mpx112_wait_without_start_fires():
    g = G(events=[E(0, "allreduce_wait", comm_uid=1, span=11)])
    (f,) = checkers.run_checkers(g)
    assert f.code == "MPX112"
    assert "no live matching" in f.message


def test_mpx112_double_wait_fires_once():
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=11),
        E(1, "allreduce_wait", comm_uid=1, span=11),
        E(2, "allreduce_wait", comm_uid=1, span=11),
    ])
    codes = [f.code for f in checkers.run_checkers(g)]
    assert codes == ["MPX112"]


def test_mpx112_clean_pairs_interleaved():
    # two in-flight handles waited out of order: still properly paired
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1),
        E(1, "reduce_scatter_start", comm_uid=1, span=2),
        E(2, "reduce_scatter_wait", comm_uid=1, span=2),
        E(3, "allreduce_wait", comm_uid=1, span=1),
    ])
    assert codes_of(g) == []


# ---------------------------------------------------------------------------
# MPX108 — jaxpr walker (duck-typed fakes)
# ---------------------------------------------------------------------------


class _Prim:
    def __init__(self, name):
        self.name = name


class _Eqn:
    def __init__(self, name, params=None):
        self.primitive = _Prim(name)
        self.params = params or {}


class _Jaxpr:
    def __init__(self, eqns):
        self.eqns = eqns


class _Closed:
    def __init__(self, jaxpr):
        self.jaxpr = jaxpr


def _cond(branches):
    return _Eqn("cond", {"branches": tuple(_Closed(b) for b in branches)})


def test_mpx108_divergent_cond_fires():
    j = _Jaxpr([_cond([_Jaxpr([_Eqn("psum")]), _Jaxpr([_Eqn("add")])])])
    (f,) = walker.check_cond_divergence(_Closed(j))
    assert f.code == "MPX108"
    assert "disagree" in f.message


def test_mpx108_negative_cases():
    # both branches communicate
    j = _Jaxpr([_cond([_Jaxpr([_Eqn("psum")]), _Jaxpr([_Eqn("ppermute")])])])
    assert walker.check_cond_divergence(_Closed(j)) == []
    # neither branch communicates
    j = _Jaxpr([_cond([_Jaxpr([_Eqn("add")]), _Jaxpr([])])])
    assert walker.check_cond_divergence(_Closed(j)) == []
    # no cond at all
    j = _Jaxpr([_Eqn("psum"), _Eqn("add")])
    assert walker.check_cond_divergence(_Closed(j)) == []


def test_walker_descends_nested_jaxprs():
    inner = _Jaxpr([_cond([_Jaxpr([_Eqn("all_gather")]), _Jaxpr([])])])
    outer = _Jaxpr([_Eqn("pjit", {"jaxpr": _Closed(inner)})])
    (f,) = walker.check_cond_divergence(_Closed(outer))
    assert f.code == "MPX108"


def test_collective_primitive_prefixes():
    assert walker.is_collective("psum")
    assert walker.is_collective("psum2")  # jax renames stay covered
    assert walker.is_collective("all_gather_invariant")
    assert not walker.is_collective("add")
    assert not walker.is_collective("cond")


# ---------------------------------------------------------------------------
# report / rendering
# ---------------------------------------------------------------------------


def test_report_render_and_partitions():
    g = G(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="f", shape=(1,)),
        E(1, "allreduce", comm_uid=1, comm_size=8, payload_bytes=1 << 20,
          algo="ring"),
    ], meta={"collective_algo": "auto", "ring_crossover_bytes": 1 << 20})
    findings = checkers.run_checkers(g)
    rep = report.Report(findings=tuple(findings), events=tuple(g.events))
    assert not rep.ok
    assert {f.code for f in rep.errors} == {"MPX101"}
    assert {f.code for f in rep.advisories} == {"MPX109"}
    text = rep.render()
    assert "MPX101" in text and "MPX109" in text and "fix:" in text
    with pytest.raises(report.AnalysisError) as ei:
        rep.raise_if_findings()
    assert {f.code for f in ei.value.findings} == {"MPX101", "MPX109"}


def test_clean_report():
    rep = report.Report()
    assert rep.ok and "clean" in rep.render()
    rep.raise_if_findings()  # no-op


def test_mpx_error_tags_and_appends_code():
    e = report.mpx_error(ValueError, "MPX105", "root 9 out of range")
    assert isinstance(e, ValueError)
    assert e.mpx_code == "MPX105"
    assert str(e).endswith("[MPX105]")
    f = report.finding_from_exception(e)
    assert f.code == "MPX105" and "root 9" in f.message
    assert report.finding_from_exception(ValueError("plain")) is None


# ---------------------------------------------------------------------------
# env mode plumbing
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_analyze_env(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE", raising=False)
    yield
    hook.set_analyze_mode(None)


def test_analyze_mode_parsing():
    assert config.analyze_mode() == "off"
    os.environ["MPI4JAX_TPU_ANALYZE"] = "WARN"  # case-insensitive
    assert config.analyze_mode() == "warn"
    os.environ["MPI4JAX_TPU_ANALYZE"] = "loud"
    with pytest.raises(ValueError, match="MPI4JAX_TPU_ANALYZE"):
        config.analyze_mode()


def test_mode_override_and_cache_token():
    assert hook.effective_mode() == "off"
    assert hook.analysis_cache_token() == ("off", "auto")
    hook.set_analyze_mode("error")
    assert hook.effective_mode() == "error"
    assert hook.analysis_cache_token() == ("error", "auto")
    # the cross-rank setting is part of the token: flipping it retraces
    os.environ["MPI4JAX_TPU_ANALYZE_RANKS"] = "off"
    try:
        assert hook.analysis_cache_token() == ("error", "off")
    finally:
        del os.environ["MPI4JAX_TPU_ANALYZE_RANKS"]
    hook.set_analyze_mode(None)
    os.environ["MPI4JAX_TPU_ANALYZE"] = "warn"
    assert hook.effective_mode() == "warn"
    with pytest.raises(ValueError, match="analyze mode"):
        hook.set_analyze_mode("loud")


def test_finish_context_warn_and_error_modes():
    class Ctx:
        pass

    def dirty_ctx(mode):
        ctx = Ctx()
        rec = hook.Recorder(mode)
        rec.events.append(E(0, "send", comm_uid=1, tag=0, dtype="f",
                            shape=(1,)))
        ctx.analysis_recorder = rec
        return ctx

    with pytest.warns(UserWarning, match="MPX101"):
        hook.finish_context(dirty_ctx("warn"), "spmd region f")
    with pytest.raises(report.AnalysisError, match="MPX101"):
        hook.finish_context(dirty_ctx("error"), "spmd region f")
    # clean stream: silent in both modes
    ctx = Ctx()
    ctx.analysis_recorder = hook.Recorder("error")
    hook.finish_context(ctx, "spmd region f")


def test_arm_context_respects_mode():
    class Ctx:
        analysis_recorder = None

    ctx = Ctx()
    hook.arm_context(ctx)
    assert ctx.analysis_recorder is None  # off: zero overhead
    hook.set_analyze_mode("warn")
    hook.arm_context(ctx)
    assert ctx.analysis_recorder is not None
    assert ctx.analysis_recorder.mode == "warn"


def test_clear_analysis_caches():
    hook.analyze_cache()["k"] = "v"
    hook.clear_analysis_caches()
    assert hook.analyze_cache() == {}
