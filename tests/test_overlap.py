"""Async comm/compute overlap (ops/_async.py): chunk plan + traced
start/wait + the mpx.overlap() region.

The chunk-split plan is pure and loads under any JAX version (isolated
loader, mirroring tests/test_fusion.py).  The traced half — start/wait
equivalence with the synchronous ops on the 8-device mesh, lazy routing
inside ``mpx.overlap()``, double-wait rejection, cache-key retraces —
needs a real ``mpi4jax_tpu`` import (jax>=0.6).
"""

import importlib
import os
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_overlap_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops", "parallel", "analysis"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    importlib.import_module(f"{_ISO_NAME}.ops._async")
    return root


ISO = _load_isolated()
asy = sys.modules[f"{_ISO_NAME}.ops._async"]
config = sys.modules[f"{_ISO_NAME}.utils.config"]

try:
    import mpi4jax_tpu  # noqa: F401

    HAS_MPX = True
except Exception:
    HAS_MPX = False

needs_mpx = pytest.mark.skipif(
    not HAS_MPX, reason="mpi4jax_tpu import refused (JAX below hard floor)"
)


@pytest.fixture(autouse=True)
def _clean_overlap_env():
    saved = os.environ.pop("MPI4JAX_TPU_OVERLAP_CHUNKS", None)
    yield
    if HAS_MPX:
        import mpi4jax_tpu as mpx

        mpx.clear_caches()
    if saved is None:
        os.environ.pop("MPI4JAX_TPU_OVERLAP_CHUNKS", None)
    else:
        os.environ["MPI4JAX_TPU_OVERLAP_CHUNKS"] = saved


# ---------------------------------------------------------------------------
# the chunk plan (pure)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,chunks", [
    (1, 1), (1, 4), (7, 2), (8, 2), (9, 2), (10, 3), (5, 8), (1024, 4),
])
def test_chunk_split_properties(n, chunks):
    sizes = asy.overlap_chunk_split(n, chunks)
    assert sum(sizes) == n
    assert len(sizes) <= max(1, chunks)
    assert all(s > 0 for s in sizes)
    # balanced: no chunk exceeds the ceil stride
    assert max(sizes) == -(-n // min(max(1, min(chunks, n)), chunks))


def test_chunk_split_exact_values():
    assert asy.overlap_chunk_split(10, 3) == [4, 4, 2]
    assert asy.overlap_chunk_split(8, 2) == [4, 4]
    assert asy.overlap_chunk_split(1, 4) == [1]


def test_overlap_cache_token_tracks_chunks():
    assert asy.overlap_cache_token() == (config.DEFAULT_OVERLAP_CHUNKS,)
    os.environ["MPI4JAX_TPU_OVERLAP_CHUNKS"] = "5"
    assert asy.overlap_cache_token() == (5,)
    os.environ["MPI4JAX_TPU_OVERLAP_CHUNKS"] = "0"
    with pytest.raises(ValueError):
        asy.overlap_cache_token()


# ---------------------------------------------------------------------------
# traced start/wait (jax>=0.6, 8-device mesh)
# ---------------------------------------------------------------------------


def _world():
    import mpi4jax_tpu as mpx

    comm = mpx.get_default_comm()
    return mpx, comm, comm.Get_size()


@needs_mpx
@pytest.mark.parametrize("op_name", ["SUM", "PROD", "MAX"])
@pytest.mark.parametrize("chunks", [1, 2, 3])
def test_start_wait_matches_allreduce(op_name, chunks, monkeypatch):
    """8-device pin: the chunked ring start/wait pair reproduces the
    synchronous allreduce bit for bit, for every chunk count."""
    import jax.numpy as jnp

    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", str(chunks))
    mpx, comm, size = _world()
    op = getattr(mpx, op_name)
    x = np.arange(1, size * 7 + 1, dtype=np.float32).reshape(size, 7) / 7.0

    def sync(v):
        s, _ = mpx.allreduce(v, op=op)
        return mpx.varying(s * 1.0)

    def split(v):
        h, _ = mpx.allreduce_start(v, op=op)
        v2 = v * 2.0  # independent compute in the gap
        s, _ = mpx.allreduce_wait(h)
        return mpx.varying(s + 0 * v2)

    want = np.asarray(mpx.run(sync, jnp.asarray(x)))
    got = np.asarray(mpx.run(split, jnp.asarray(x)))
    np.testing.assert_allclose(want, got, rtol=1e-6)


@needs_mpx
def test_start_wait_callable_op_falls_back():
    """Callable reductions cannot ring-chunk: the start emits the whole
    butterfly and the pair stays correct."""
    import jax.numpy as jnp

    mpx, comm, size = _world()

    def f(a, b):
        return a + b

    x = np.arange(size * 3, dtype=np.float32).reshape(size, 3)

    def split(v):
        h, _ = mpx.allreduce_start(v, op=f)
        s, _ = mpx.allreduce_wait(h)
        return mpx.varying(s * 1.0)

    got = np.asarray(mpx.run(split, jnp.asarray(x)))
    want = np.broadcast_to(x.sum(axis=0), (size, 3))
    np.testing.assert_allclose(got, want, rtol=1e-6)


@needs_mpx
@pytest.mark.parametrize("chunks", [1, 2, 4])
def test_reduce_scatter_start_wait_matches(chunks, monkeypatch):
    import jax.numpy as jnp

    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", str(chunks))
    mpx, comm, size = _world()
    x = np.arange(size * size * 3, dtype=np.float32).reshape(size, size, 3)

    def sync(v):
        s, _ = mpx.reduce_scatter(v, op=mpx.SUM)
        return mpx.varying(s * 1.0)

    def split(v):
        h, _ = mpx.reduce_scatter_start(v, op=mpx.SUM)
        s, _ = mpx.reduce_scatter_wait(h)
        return mpx.varying(s * 1.0)

    want = np.asarray(mpx.run(sync, jnp.asarray(x)))
    got = np.asarray(mpx.run(split, jnp.asarray(x)))
    np.testing.assert_allclose(want, got, rtol=1e-6)


@needs_mpx
@pytest.mark.parametrize("chunks", [1, 2, 4])
def test_alltoall_start_wait_matches(chunks, monkeypatch):
    """8-device pin: the chunked pairwise start/wait pair reproduces the
    synchronous alltoall BIT FOR BIT (pure routing), for every chunk
    count and an odd per-block payload (chunk-split reassembly)."""
    import jax.numpy as jnp

    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", str(chunks))
    mpx, comm, size = _world()
    x = np.arange(size * size * 5, dtype=np.float32).reshape(size, size, 5)

    def sync(v):
        s, _ = mpx.alltoall(v)
        return mpx.varying(s * 1.0)

    def split(v):
        h, _ = mpx.alltoall_start(v)
        w = v * 2.0  # independent compute in the gap
        s, _ = mpx.alltoall_wait(h)
        return mpx.varying(s + 0 * w)

    want = np.asarray(mpx.run(sync, jnp.asarray(x)))
    got = np.asarray(mpx.run(split, jnp.asarray(x)))
    np.testing.assert_array_equal(want, got)
    np.testing.assert_array_equal(got, x.transpose(1, 0, 2))


@needs_mpx
def test_alltoall_start_wait_hier_composition(monkeypatch):
    """Under a faked 2-host topology with the crossover dropped, every
    chunk's start phase runs the two-level exchange (intra transpose +
    DCN exchange at start, reassembly-only wait) — results stay the
    exact permutation."""
    import jax.numpy as jnp

    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1")
    mpx, comm, size = _world()
    if size < 4 or size % 2:
        pytest.skip("needs an even mesh of >= 4 for the 2-host fake")
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    x = np.arange(size * size * 3, dtype=np.float32).reshape(size, size, 3)

    def split(v):
        h, _ = mpx.alltoall_start(v)
        s, _ = mpx.alltoall_wait(h)
        return mpx.varying(s * 1.0)

    got = np.asarray(mpx.run(split, jnp.asarray(x)))
    np.testing.assert_array_equal(got, x.transpose(1, 0, 2))


@needs_mpx
def test_alltoall_double_wait_raises():
    import jax.numpy as jnp

    mpx, comm, size = _world()

    def prog(v):
        h, _ = mpx.alltoall_start(v)
        s, _ = mpx.alltoall_wait(h)
        with pytest.raises(RuntimeError, match="MPX112"):
            mpx.alltoall_wait(h)
        return mpx.varying(s * 1.0)

    np.asarray(mpx.run(prog, jnp.ones((size, size, 2), jnp.float32)))


@needs_mpx
def test_overlap_region_splits_alltoall():
    """Inside mpx.overlap(), a plain alltoall auto-splits into the
    start/deferred-wait pair and materializes on first use."""
    import jax.numpy as jnp

    mpx, comm, size = _world()
    x = np.arange(size * size * 2, dtype=np.float32).reshape(size, size, 2)

    def prog(v):
        with mpx.overlap():
            s, _ = mpx.alltoall(v)
            w = v * 3.0  # overlaps the exchange phases
            out = s + w * 0
        return mpx.varying(out)

    got = np.asarray(mpx.run(prog, jnp.asarray(x)))
    np.testing.assert_array_equal(got, x.transpose(1, 0, 2))


@needs_mpx
def test_overlap_region_lazy_routing():
    """Inside mpx.overlap(), plain allreduce auto-splits and the result
    materializes on first use; unforced handles are waited at region
    exit."""
    import jax.numpy as jnp

    mpx, comm, size = _world()
    x = np.arange(size * 4, dtype=np.float32).reshape(size, 4)

    def prog(v):
        with mpx.overlap():
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            w = v * 3.0  # overlaps the wire phases
            out = s + w * 0
        return mpx.varying(out)

    got = np.asarray(mpx.run(prog, jnp.asarray(x)))
    want = np.broadcast_to(x.sum(axis=0), (size, 4))
    np.testing.assert_allclose(got, want, rtol=1e-6)


@needs_mpx
def test_overlap_region_auto_waits_unused_results():
    """A result never used inside the region is still waited at exit, so
    its collective is not dead-code-eliminated out of the analysis/token
    stream (MPX112 stays clean)."""
    import jax.numpy as jnp

    mpx, comm, size = _world()
    x = np.ones((size, 3), np.float32)

    def prog(v):
        with mpx.overlap():
            s, _ = mpx.allreduce(v, op=mpx.SUM)
        return mpx.varying(s * 1.0)  # first use AFTER the region

    got = np.asarray(mpx.run(prog, jnp.asarray(x)))
    np.testing.assert_allclose(got, np.full((size, 3), size), rtol=1e-6)


@needs_mpx
def test_double_wait_raises():
    import jax.numpy as jnp

    mpx, comm, size = _world()

    def prog(v):
        h, _ = mpx.allreduce_start(v, op=mpx.SUM)
        s, _ = mpx.allreduce_wait(h)
        with pytest.raises(RuntimeError, match="MPX112"):
            mpx.allreduce_wait(h)
        return mpx.varying(s * 1.0)

    np.asarray(mpx.run(prog, jnp.ones((size, 2), jnp.float32)))


@needs_mpx
def test_start_wait_requires_parallel_region():
    import jax.numpy as jnp

    mpx, comm, size = _world()
    with pytest.raises(RuntimeError, match="parallel region"):
        mpx.allreduce_start(jnp.ones((size, 2)), op=mpx.SUM)


@needs_mpx
def test_overlap_requires_managed_region():
    import mpi4jax_tpu as mpx

    with pytest.raises(RuntimeError, match="managed parallel region"):
        with mpx.overlap():
            pass


@needs_mpx
def test_chunks_flip_retraces_eager_program(monkeypatch):
    """MPI4JAX_TPU_OVERLAP_CHUNKS is folded into the eager cache key:
    flipping it must retrace (mirrors the fusion/telemetry retrace
    pins)."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.clear_caches()
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM)
    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", "3")
    mpx.allreduce(x, op=mpx.SUM)
    monkeypatch.delenv("MPI4JAX_TPU_OVERLAP_CHUNKS")
    mpx.allreduce(x, op=mpx.SUM)  # back to the first program
    s = mpx.cache_stats()
    assert s["misses"] == 2 and s["hits"] == 1


@needs_mpx
def test_overlap_telemetry_chunk_meter(monkeypatch):
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", "2")
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    try:
        def prog(v):
            h, _ = mpx.allreduce_start(v, op=mpx.SUM)
            s, _ = mpx.allreduce_wait(h)
            return mpx.varying(s * 1.0)

        mpx.run(prog, jnp.ones((8, 16), jnp.float32))
        meters = mpx.telemetry.snapshot()["meters"]
        chunk_meters = {k: v for k, v in meters.items()
                        if k.startswith("overlap.allreduce.")}
        assert sum(chunk_meters.values()) == 2, meters
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()
