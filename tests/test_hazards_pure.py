"""Dataflow hazard verifier: the pure-Python half (docs/analysis.md
"Dataflow hazards").

Positive/negative matrix for the two halves of the hazard verifier —
the graph-side buffer checkers (analysis/hazards.py: MPX139 donation
races against open async spans, MPX140 use-after-donate) driven by
hand-built event streams with donation records, and the jaxpr-side
taint pass (analysis/dataflow.py: MPX141 rank-local schedule gates,
MPX142 approximate lineage) driven by duck-typed fake jaxprs — all
loaded under a private package name (the tests/test_analysis_pure.py
isolated loader) so these run even where the installed JAX is below the
package's floor.  The traced integration half — the same hazards driven
through ``mpx.analyze`` and the ambient env=error path on the 8-device
mesh — lives in tests/test_hazards.py.
"""

import importlib
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_hazards_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "ops", "parallel", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._fusion", "analysis.report",
                "analysis.graph", "analysis.checkers", "analysis.walker",
                "analysis.dataflow", "analysis.hazards", "analysis.hook",
                "analysis.schedule", "analysis.matcher",
                "analysis.progress", "resilience.elastic",
                "analysis.crossrank", "parallel.rankspec"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
report = sys.modules[f"{_ISO_NAME}.analysis.report"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
dataflow = sys.modules[f"{_ISO_NAME}.analysis.dataflow"]
hazards = sys.modules[f"{_ISO_NAME}.analysis.hazards"]
crossrank = sys.modules[f"{_ISO_NAME}.analysis.crossrank"]

E = graph.CollectiveEvent
G = graph.CollectiveGraph


# ---------------------------------------------------------------------------
# duck-typed fake jaxprs (the tests/test_analysis_pure.py walker fakes,
# extended with invars/outvars/avals for the taint environment)
# ---------------------------------------------------------------------------


class _Prim:
    def __init__(self, name):
        self.name = name


class _Aval:
    def __init__(self, dtype=None, vma=None):
        self.dtype = dtype
        self.vma = vma


class _Var:
    def __init__(self, aval=None):
        self.aval = aval


class _Lit:
    def __init__(self, val=0):
        self.val = val


class _Eqn:
    def __init__(self, name, invars=(), outvars=(), params=None):
        self.primitive = _Prim(name)
        self.invars = list(invars)
        self.outvars = list(outvars)
        self.params = params or {}


class _Jaxpr:
    def __init__(self, eqns, invars=(), outvars=()):
        self.eqns = eqns
        self.invars = list(invars)
        self.outvars = list(outvars)


class _Closed:
    def __init__(self, jaxpr):
        self.jaxpr = jaxpr


def _branch(*coll_names):
    """One cond branch taking one operand and issuing the named
    collectives in a chain."""
    v = _Var()
    eqns, cur = [], v
    for name in coll_names:
        nxt = _Var()
        eqns.append(_Eqn(name, [cur], [nxt]))
        cur = nxt
    return _Closed(_Jaxpr(eqns, invars=[v], outvars=[cur]))


def _gate(pred, operand, left=("psum", "ppermute"), right=("psum",)):
    """A cond whose branches issue the given collective schedules."""
    return _Eqn("cond", [pred, operand], [_Var()],
                {"branches": (_branch(*left), _branch(*right))})


def _findings(eqns, **kw):
    return dataflow.hazard_jaxpr_findings(
        _Closed(_Jaxpr(eqns)), **kw)


# ---------------------------------------------------------------------------
# MPX141 — rank-local lineage gating the collective schedule
# ---------------------------------------------------------------------------


def test_mpx141_axis_index_seed_fires():
    r, p, x = _Var(), _Var(), _Var()
    fs = _findings([
        _Eqn("axis_index", [], [r]),
        _Eqn("gt", [r, _Lit()], [p]),
        _gate(p, x),
    ])
    (f,) = fs
    assert f.code == "MPX141"
    assert report.CODES["MPX141"].severity == report.ERROR
    assert "different collective schedules" in f.message
    # the rendered per-branch signatures name the differing schedules
    assert "psum" in f.message and "ppermute" in f.message
    # the taint frontier runs seed -> sink
    assert "axis_index" in f.frontier[0]
    assert "cond predicate" in f.frontier[-1]
    assert "taint:" in f.render()


def test_mpx141_silent_when_schedules_agree():
    r, p, x = _Var(), _Var(), _Var()
    fs = _findings([
        _Eqn("axis_index", [], [r]),
        _Eqn("gt", [r, _Lit()], [p]),
        _gate(p, x, left=("psum",), right=("psum",)),
    ])
    assert fs == []


def test_mpx141_silent_on_untainted_predicate():
    p, x = _Var(), _Var()
    assert _findings([_gate(p, x)]) == []


def test_mpx141_replicating_collective_launders():
    # psum replicates its result across the axis: the gate is now
    # rank-invariant, so no hazard
    r, s, p, x = _Var(), _Var(), _Var(), _Var()
    fs = _findings([
        _Eqn("axis_index", [], [r]),
        _Eqn("psum", [r], [s]),
        _Eqn("gt", [s, _Lit()], [p]),
        _gate(p, x),
    ])
    assert fs == []


def test_mpx141_psum_scatter_does_not_launder():
    # psum_scatter leaves a DIFFERENT shard on every rank — the prefix
    # match must not mistake it for a replicating reduction
    r, s, p, x = _Var(), _Var(), _Var(), _Var()
    fs = _findings([
        _Eqn("axis_index", [], [r]),
        _Eqn("psum_scatter", [r], [s]),
        _Eqn("gt", [s, _Lit()], [p]),
        _gate(p, x),
    ])
    assert [f.code for f in fs] == ["MPX141"]


def test_mpx141_implicit_vma_seed():
    # shard_map's collective-varying type IS a rank-local verdict: a
    # value typed vma={'x'} seeds without any axis_index in sight (the
    # EF-residual lineage of examples/broken/ef_divergent_gate.py)
    p, x = _Var(_Aval(vma={"x"})), _Var()
    (f,) = _findings([_gate(p, x)])
    assert f.code == "MPX141"
    assert "vma={x}" in f.frontier[0]


def test_replicates_table():
    assert dataflow.replicates("psum")
    assert dataflow.replicates("psum2")
    assert dataflow.replicates("all_gather")
    assert dataflow.replicates("pmax")
    assert not dataflow.replicates("psum_scatter")
    assert not dataflow.replicates("ppermute")
    assert not dataflow.replicates("all_to_all")


def test_collective_signature_counts_nested():
    inner = _Jaxpr([_Eqn("psum"), _Eqn("psum")])
    outer = _Jaxpr([_Eqn("pjit", params={"jaxpr": _Closed(inner)}),
                    _Eqn("ppermute")])
    assert dataflow.collective_signature(outer) == (
        ("ppermute", 1), ("psum", 2))


# ---------------------------------------------------------------------------
# MPX142 — approximate lineage at exactness-required sinks
# ---------------------------------------------------------------------------


def _downcast_chain(pred_sink=True):
    x = _Var(_Aval(dtype="float32"))
    y, p, z = _Var(), _Var(), _Var()
    eqns = [_Eqn("convert_element_type", [x], [y],
                 {"new_dtype": "bfloat16"})]
    if pred_sink:
        eqns += [_Eqn("gt", [y, _Lit()], [p]),
                 _gate(p, z, left=("psum",), right=("psum",))]
    return eqns, y


def test_mpx142_arming_gate():
    eqns, _ = _downcast_chain()
    # unarmed: a float downcast is ordinary mixed precision
    assert _findings(eqns) == []
    fs = _findings(eqns, approx_armed=True)
    (f,) = fs
    assert f.code == "MPX142"
    assert report.CODES["MPX142"].severity == report.ADVISORY
    assert "lossy codec downcast" in f.frontier[0]


def test_mpx142_index_sink():
    eqns, y = _downcast_chain(pred_sink=False)
    arr, out = _Var(), _Var()
    eqns.append(_Eqn("dynamic_slice", [arr, y], [out]))
    (f,) = _findings(eqns, approx_armed=True)
    assert f.code == "MPX142" and f.op == "dynamic_slice"
    assert "index operand" in f.message


def test_mpx142_approx_survives_reduction():
    # replication launders RANK but APPROX error survives the psum
    eqns, y = _downcast_chain(pred_sink=False)
    s, p, z = _Var(), _Var(), _Var()
    eqns += [_Eqn("psum", [y], [s]),
             _Eqn("gt", [s, _Lit()], [p]),
             _gate(p, z, left=("psum",), right=("psum",))]
    (f,) = _findings(eqns, approx_armed=True)
    assert f.code == "MPX142"


def test_upcast_never_seeds():
    x = _Var(_Aval(dtype="bfloat16"))
    y, p, z = _Var(), _Var(), _Var()
    fs = _findings([
        _Eqn("convert_element_type", [x], [y], {"new_dtype": "float32"}),
        _Eqn("gt", [y, _Lit()], [p]),
        _gate(p, z, left=("psum",), right=("psum",)),
    ], approx_armed=True)
    assert fs == []


def test_graph_arms_approx():
    assert not dataflow.graph_arms_approx(None)
    assert not dataflow.graph_arms_approx(G(events=[]))
    assert not dataflow.graph_arms_approx(
        G(events=[], meta={"compress": "off"}))
    assert dataflow.graph_arms_approx(
        G(events=[], meta={"compress": "bf16"}))
    assert dataflow.graph_arms_approx(
        G(events=[E(0, "allreduce", codec="fp8")]))
    assert dataflow.graph_arms_approx(
        G(events=[E(0, "allreduce", extra={"ef": True})]))


# ---------------------------------------------------------------------------
# propagation machinery: sub-jaxpr descent, scan feedback, trail cap
# ---------------------------------------------------------------------------


def test_taint_descends_pjit():
    # the gate sits INSIDE a pjit wrapper; taint maps through the binder
    r, p = _Var(), _Var()
    inner_in, inner_p, inner_x = _Var(), _Var(), _Var()
    inner = _Jaxpr([_Eqn("gt", [inner_in, _Lit()], [inner_p]),
                    _gate(inner_p, inner_x)],
                   invars=[inner_in], outvars=[inner_p])
    fs = _findings([
        _Eqn("axis_index", [], [r]),
        _Eqn("pjit", [r], [p], {"jaxpr": _Closed(inner)}),
    ])
    assert [f.code for f in fs] == ["MPX141"]


def test_scan_carry_feedback():
    # the carry only becomes rank-local on iteration N+1: round one sees
    # an untainted carry binder, the feedback round replays the body
    # with the carry-output taint fed back in and catches the gate
    c, cx = _Var(), _Var()
    a = _Var()
    body = _Jaxpr([_gate(c, cx),
                   _Eqn("axis_index", [], [a])],
                  invars=[c], outvars=[a])
    x0 = _Var()
    fs = _findings([
        _Eqn("scan", [x0], [_Var()],
             {"jaxpr": _Closed(body), "num_carry": 1, "num_consts": 0}),
    ])
    assert [f.code for f in fs] == ["MPX141"]


def test_frontier_trail_caps_with_elision():
    r, p, x = _Var(), _Var(), _Var()
    eqns = [_Eqn("axis_index", [], [r])]
    cur = r
    for _ in range(3 * dataflow._TRAIL_CAP):
        nxt = _Var()
        eqns.append(_Eqn("sin", [cur], [nxt]))
        cur = nxt
    eqns += [_Eqn("gt", [cur, _Lit()], [p]), _gate(p, x)]
    (f,) = _findings(eqns)
    assert f.code == "MPX141"
    assert len(f.frontier) <= dataflow._TRAIL_CAP + 2
    assert dataflow._ELLIPSIS in f.frontier
    # the seed end and the live end both survive the elision
    assert "axis_index" in f.frontier[0]
    assert "cond predicate" in f.frontier[-1]


# ---------------------------------------------------------------------------
# MPX139 — donation while an open async span holds the buffer
# ---------------------------------------------------------------------------

_BUF_A, _BUF_B = 0xA11, 0xB22


def _donation(pos, ids, where="pinned call 'scale'"):
    return (pos, frozenset(ids), where)


def test_mpx139_fires_between_start_and_wait():
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1,
          buffers=(_BUF_A, _BUF_B)),
        E(1, "allreduce_wait", comm_uid=1, span=1),
    ], meta={"donations": (_donation(1, {_BUF_B}),)})
    fs = [f for f in checkers.run_checkers(g) if f.code == "MPX139"]
    (f,) = fs
    assert "write-after-start race" in f.message
    assert "pinned call 'scale'" in f.message
    assert "allreduce_wait" in f.suggestion
    # buffer ids are equality handles only — never rendered
    assert hex(_BUF_B)[2:] not in f.render()


def test_mpx139_unwaited_span_still_fires():
    # a span crossing an mpx.overlap() boundary has no wait in-stream
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1, buffers=(_BUF_A,)),
    ], meta={"donations": (_donation(1, {_BUF_A}),)})
    assert [f.code for f in checkers.run_checkers(g)
            if f.code == "MPX139"] == ["MPX139"]


def test_mpx139_negatives():
    # donation BEFORE the span opens: the start captured fresh storage
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1, buffers=(_BUF_A,)),
        E(1, "allreduce_wait", comm_uid=1, span=1),
    ], meta={"donations": (_donation(0, {_BUF_A}),)})
    assert not [f for f in checkers.run_checkers(g) if f.code == "MPX139"]
    # donation AFTER the wait: the span released the buffer
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1, buffers=(_BUF_A,)),
        E(1, "allreduce_wait", comm_uid=1, span=1),
        E(2, "allreduce", comm_uid=1),
    ], meta={"donations": (_donation(2, {_BUF_A}),)})
    assert not [f for f in checkers.run_checkers(g) if f.code == "MPX139"]
    # donation of a buffer the span does not hold
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1, buffers=(_BUF_A,)),
        E(1, "allreduce_wait", comm_uid=1, span=1),
    ], meta={"donations": (_donation(1, {_BUF_B}),)})
    assert not [f for f in checkers.run_checkers(g) if f.code == "MPX139"]


def test_mpx139_fused_member_buffers():
    # a fusion flush records the MEMBER buffer ids on the packed event,
    # so donating a bucket member mid-span is still seen
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=7, fused_members=2,
          buffers=(_BUF_A, _BUF_B)),
        E(1, "allreduce_wait", comm_uid=1, span=7),
    ], meta={"donations": (_donation(1, {_BUF_B}),)})
    assert [f.code for f in checkers.run_checkers(g)
            if f.code == "MPX139"] == ["MPX139"]


# ---------------------------------------------------------------------------
# MPX140 — value consumed after the pinned call that donated it
# ---------------------------------------------------------------------------


def test_mpx140_fires():
    g = G(events=[
        E(0, "allreduce", comm_uid=1, buffers=(_BUF_A,)),
    ], meta={"donations": (_donation(0, {_BUF_A}),)})
    (f,) = [f for f in checkers.run_checkers(g) if f.code == "MPX140"]
    assert "already donated" in f.message
    assert "donate_argnums" in f.suggestion


def test_mpx140_negative_consume_before_donation():
    g = G(events=[
        E(0, "allreduce", comm_uid=1, buffers=(_BUF_A,)),
    ], meta={"donations": (_donation(1, {_BUF_A}),)})
    assert not [f for f in checkers.run_checkers(g) if f.code == "MPX140"]


def test_no_donations_no_hazard_findings():
    # without donation records neither checker walks anything — the
    # byte-identity contract keeps "donations" out of meta entirely
    g = G(events=[
        E(0, "allreduce_start", comm_uid=1, span=1, buffers=(_BUF_A,)),
        E(1, "allreduce_wait", comm_uid=1, span=1),
    ])
    assert "donations" not in g.meta
    assert not [f for f in checkers.run_checkers(g)
                if f.code in report.HAZARD_GRAPH_CODES]


def test_hazard_findings_wrapper_arms_from_graph():
    eqns, _ = _downcast_chain()
    closed = _Closed(_Jaxpr(eqns))
    armed = G(events=[], meta={"compress": "bf16"})
    assert [f.code for f in hazards.hazard_findings(closed, armed)] \
        == ["MPX142"]
    assert hazards.hazard_findings(closed, G(events=[])) == []


# ---------------------------------------------------------------------------
# cross-rank dedup: the would-diverge rank pair
# ---------------------------------------------------------------------------


def _divergent_closed():
    r, p, x = _Var(), _Var(), _Var()
    return _Closed(_Jaxpr([
        _Eqn("axis_index", [], [r]),
        _Eqn("gt", [r, _Lit()], [p]),
        _gate(p, x),
    ]))


def test_per_rank_mpx141_names_rank_pair():
    closed = {0: _Closed(_Jaxpr([])), 1: _divergent_closed(),
              3: _divergent_closed()}
    fs = crossrank.per_rank_hazard_findings(closed, {})
    (f,) = fs
    assert f.code == "MPX141"
    assert f.message.endswith("(ranks 1 and 3 would diverge here)")


def test_per_rank_mpx141_single_rank_cites_successor():
    closed = {2: _divergent_closed()}
    (f,) = crossrank.per_rank_hazard_findings(closed, {})
    assert f.message.endswith("(ranks 2 and 3 would diverge here)")


# ---------------------------------------------------------------------------
# report surface
# ---------------------------------------------------------------------------


def test_report_hazards_partition_and_json():
    g = G(events=[
        E(0, "allreduce", comm_uid=1, buffers=(_BUF_A,)),
    ], meta={"donations": (_donation(0, {_BUF_A}),)})
    taint = dataflow.hazard_jaxpr_findings(_Closed(_Jaxpr([
        _Eqn("axis_index", [], [_v1 := _Var()]),
        _Eqn("gt", [_v1, _Lit()], [_v2 := _Var()]),
        _gate(_v2, _Var()),
    ])))
    findings = tuple(checkers.run_checkers(g)) + tuple(taint)
    rep = report.Report(findings=findings, events=tuple(g.events))
    assert {f.code for f in rep.hazards} >= {"MPX140", "MPX141"}
    payload = rep.to_json()
    by_code = {f["code"]: f for f in payload["findings"]}
    assert "frontier" in by_code["MPX141"]
    assert "frontier" not in by_code["MPX140"]
