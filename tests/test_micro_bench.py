"""Smoke test for benchmarks/micro.py — it must keep producing numbers.

VERDICT r2: micro.py had never been executed by CI, so it could silently
rot.  Run both sweeps at tiny sizes on the test mesh and check the output
schema matches what benchmarks/results/*.json commits.
"""

import os
import sys

import jax

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "benchmarks")
)

import micro  # noqa: E402

import mpi4jax_tpu as mpx  # noqa: E402


def _world_comm():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def test_bench_allreduce_schema():
    comm = _world_comm()
    rows = micro.bench_allreduce(comm, sizes_mb=[0.0001], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["time_us"] > 0
    # tiny payloads round the bandwidth to 0.0 — only presence is asserted
    assert (r["bus_gb_s"] is None) == (comm.Get_size() == 1)


def test_bench_sendrecv_schema():
    comm = _world_comm()
    rows = micro.bench_sendrecv_ring(comm, sizes_kb=[0.004], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["hop_us"] > 0
    assert (r["link_gb_s"] is None) == (comm.Get_size() == 1)


def test_bench_prod_and_split_schema():
    comm = _world_comm()
    rows = micro.bench_prod_and_split(comm, sizes_mb=[0.0001], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["prod_us"] > 0
    assert (r["prod_split_us"] is None) == (comm.Get_size() == 1)
    if r["prod_split_us"] is not None:
        assert r["prod_split_us"] > 0


def test_bench_allreduce_algos_schema():
    # force-compiles BOTH CollectivePermute algorithms (butterfly + ring)
    # at a tiny size: a lowering regression in either fails here, fast
    comm = _world_comm()
    saved = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    rows = micro.bench_allreduce_algos(comm, sizes_mb=[0.0001], iters=2)
    assert os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO") == saved  # restored
    assert len(rows) == 1
    r = rows[0]
    assert r["butterfly_us"] > 0 and r["ring_us"] > 0
    assert (r["ring_speedup"] is None) == (comm.Get_size() == 1)


def test_bench_fusion_schema():
    # compiles the fused AND unfused programs at a tiny size: a deferral
    # or packing regression in the fusion layer fails here, fast
    comm = _world_comm()
    saved = os.environ.get("MPI4JAX_TPU_FUSION")
    rows = micro.bench_fusion(comm, counts=(4,), size_kb=0.02, iters=1)
    assert os.environ.get("MPI4JAX_TPU_FUSION") == saved  # restored
    assert len(rows) == 1
    r = rows[0]
    assert r["count"] == 4
    assert r["unfused_us_per_op"] > 0 and r["fused_us_per_op"] > 0
    assert r["fused_speedup"] > 0


def test_bench_overlap_schema():
    comm = _world_comm()
    rows = micro.bench_overlap(comm, sizes_mb=[0.0001], iters=2,
                               compute_dim=8)
    assert len(rows) == 1
    r = rows[0]
    assert r["monolithic_us"] > 0 and r["overlap_us"] > 0
    assert r["chunks"] >= 1 and r["overlap_speedup"] > 0


def test_bench_hierarchy_schema():
    # compiles the flat ring AND the forced two-level lowering under a
    # faked 2x4 host topology at a tiny size: a hierarchy regression in
    # either fails here, fast; a topology spec that does not cover the
    # mesh is skipped, not an error (docs/topology.md)
    comm = _world_comm()
    saved_topo = os.environ.get("MPI4JAX_TPU_TOPOLOGY")
    saved_algo = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    rows = micro.bench_hierarchy(comm, sizes_mb=[0.0001],
                                 topologies=("2x4", "3x9"), iters=2)
    assert os.environ.get("MPI4JAX_TPU_TOPOLOGY") == saved_topo  # restored
    assert os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO") == saved_algo
    assert len(rows) == 1  # 3x9 covers 27 ranks, not this mesh: skipped
    r = rows[0]
    assert r["topology"] == "2x4"  # the topology stamp --save commits
    assert r["flat_us"] > 0 and r["hier_us"] > 0
    assert (r["hier_speedup"] is None) == (comm.Get_size() == 1)


def test_bench_alltoall_schema():
    # compiles all three alltoall execution shapes — flat single
    # exchange, the forced two-level lowering, and the chunked async
    # start/wait split — under a faked 2x4 host topology at a tiny
    # size, and checks the modeled DCN byte/message columns ride every
    # uniform-topology row (docs/moe.md); a non-covering spec is
    # skipped, not an error
    comm = _world_comm()
    saved = {k: os.environ.get(k) for k in
             ("MPI4JAX_TPU_TOPOLOGY", "MPI4JAX_TPU_COLLECTIVE_ALGO",
              "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES")}
    rows = micro.bench_alltoall(comm, sizes_mb=[0.0001],
                                topologies=("2x4", "3x9"), iters=2)
    for k, v in saved.items():
        assert os.environ.get(k) == v, k  # restored
    assert len(rows) == 1  # 3x9 covers 27 ranks, not this mesh: skipped
    r = rows[0]
    assert r["topology"] == "2x4"
    assert r["flat_us"] > 0 and r["hier_us"] > 0 and r["async_us"] > 0
    assert (r["hier_speedup"] is None) == (comm.Get_size() == 1)
    # the modeled DCN columns: the 1/r message aggregation is stamped
    # into every saved row (the acceptance artifact's claim)
    assert r["dcn_msgs_flat"] == r["dcn_msgs_hier"] * r["dcn_msg_reduction"]
    assert r["dcn_msg_reduction"] == 4  # 2x4: r = 4
    assert r["dcn_bytes_hier"] <= r["dcn_bytes_flat"]


def test_alltoall_replay_artifact_current(tmp_path):
    # the committed cost-model replay (BENCH_alltoall.json) must be
    # reproducible from its embedded recipe and carry the acceptance
    # invariants: 1/r DCN message reduction on every row, overlapped
    # MoE step beating the synchronous one
    import json
    import pathlib
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent.parent
    committed = json.loads((repo / "BENCH_alltoall.json").read_text())
    assert committed["schema"] == "mpx-alltoall-replay/1"
    for row in committed["sweep"]:
        assert row["dcn_msgs_flat"] == \
            row["dcn_msgs_hier"] * row["dcn_msg_reduction"], row
    for row in committed["moe_step"]:
        assert row["overlap_speedup"] > 1.0, row
    out = tmp_path / "replay.json"
    subprocess.run(
        [sys.executable, str(repo / "benchmarks" / "alltoall_replay.py"),
         "--out", str(out)],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert json.loads(out.read_text()) == committed


def test_bench_compression_schema():
    # runs the codec sweep at a tiny size under a faked 2x4 topology:
    # every {off, bf16, fp8} row carries the logical/wire byte split
    # (from ops/_codec.wire_bytes — the shared byte truth), a modeled
    # DCN time, and a measured roundtrip error that is exactly zero
    # only for the exact codec (docs/compression.md)
    from mpi4jax_tpu.ops import _codec

    comm = _world_comm()
    rows = micro.bench_compression(comm, sizes_mb=[0.01], iters=2)
    assert [r["codec"] for r in rows] == ["off", "bf16", "fp8"]
    for r in rows:
        assert r["size_mb"] == 0.01 and r["topology"] == "2x4"
        assert r["wire_dcn_bytes"] == _codec.wire_bytes(
            r["logical_dcn_bytes"], None if r["codec"] == "off"
            else r["codec"])
        assert r["modeled_dcn_us"] > 0
        if r["codec"] == "off":
            assert r["rel_err"] == 0.0
            assert r["wire_dcn_bytes"] == r["logical_dcn_bytes"]
        else:
            assert 0 < r["rel_err"] < 1.0
            assert r["wire_dcn_bytes"] * 2 <= r["logical_dcn_bytes"]


def test_compress_replay_artifact_current(tmp_path):
    # the committed compression replay (BENCH_compress.json) must be
    # reproducible from its embedded recipe and carry the acceptance
    # invariants: >= 2x DCN wire reduction for both codecs, compressed
    # loss curves within the stated parity tolerance of the exact one
    import json
    import pathlib
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent.parent
    committed = json.loads((repo / "BENCH_compress.json").read_text())
    assert committed["schema"] == "mpx-compress-replay/1"
    for row in committed["wire_sweep"]:
        if row["codec"] != "off":
            assert row["wire_reduction"] >= 2.0, row
    for codec, p in committed["convergence"]["parity"].items():
        assert p["max_rel_gap"] <= p["tolerance"], (codec, p)
    out = tmp_path / "replay.json"
    subprocess.run(
        [sys.executable, str(repo / "benchmarks" / "compress_replay.py"),
         "--out", str(out)],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert json.loads(out.read_text()) == committed


def test_bench_dispatch_schema():
    # compiles all three execution surfaces — eager one-op, spmd, and
    # the mpx.compile-pinned artifact — for the same allreduce at a tiny
    # size: a pinning or dispatch-path regression fails here, fast
    comm = _world_comm()
    rows = micro.bench_dispatch(comm, sizes_kb=[0.004], iters=3)
    assert len(rows) == 1
    r = rows[0]
    assert r["eager_us"] > 0 and r["spmd_us"] > 0 and r["pinned_us"] > 0
    assert r["pinned_vs_spmd"] is not None and r["pinned_vs_spmd"] > 0
    # the sweep pinned at least one program this process
    assert mpx.cache_stats()["aot"]["pins"] >= 1


def test_bench_dispatch_unroll_schema():
    # compiles the same one-allreduce step pinned at two megastep trip
    # counts (mpx.compile(fn, ..., unroll=N)) — a megastep lowering or
    # amortization-math regression fails here, fast (docs/aot.md
    # "Megastep execution"); the full 1/8 amortization assert at
    # unroll=64 lives in the CI aot lane against the saved sweep
    comm = _world_comm()
    du = micro.bench_dispatch_unroll(comm, unrolls=(1, 4), size_kb=0.004,
                                     iters=3)
    assert set(du) == {"size_kb", "onchip_per_step_us", "rows"}
    assert du["onchip_per_step_us"] >= 0
    assert [r["unroll"] for r in du["rows"]] == [1, 4]
    for r in du["rows"]:
        assert r["megastep_us"] > 0 and r["per_step_us"] > 0
        assert r["per_step_host_us"] >= 0
        assert isinstance(r["fast_path"], bool)
    # amortization direction: per-step host cost must not grow with N
    assert (du["rows"][1]["per_step_host_us"]
            <= du["rows"][0]["per_step_host_us"] + 1e-9)
    assert mpx.cache_stats()["aot"]["pins"] >= 2


def test_bench_health_overhead_schema():
    # all four telemetry configurations of the same eager allreduce at a
    # tiny size — a dispatch-path regression in any tier fails here; the
    # 10% counters+ring bound itself is asserted in the CI smoke lane
    # where iteration counts make the ratio meaningful
    comm = _world_comm()
    saved = {k: os.environ.get(k)
             for k in ("MPI4JAX_TPU_HEALTH", "MPI4JAX_TPU_FLIGHT_RING")}
    rows = micro.bench_health_overhead(comm, sizes_kb=[0.004], iters=2)
    assert len(rows) == 1
    r = rows[0]
    for col in ("off_us", "counters_us", "counters_ring_us", "events_us"):
        assert r[col] > 0, col
    assert r["ring_overhead_ratio"] is not None
    assert r["ring_overhead_ratio"] > 0
    # the sweep must leave no telemetry or health state behind
    assert mpx.telemetry.effective_mode() == "off"
    for k, v in saved.items():
        assert os.environ.get(k) == v, k


def test_health_replay_artifact_current(tmp_path):
    # the committed record-volume replay (BENCH_health.json) must be
    # reproducible from its embedded recipe and carry the overhead
    # invariants: counters+ring pushes exactly one ring record per
    # dispatch and adds ZERO journal records over counters-only
    import json
    import pathlib
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent.parent
    committed = json.loads((repo / "BENCH_health.json").read_text())
    assert committed["schema"] == "mpx-health-replay/1"
    by_mode = {(r["mode"], r["health"]): r for r in committed["configs"]}
    ring = by_mode[("counters", "on")]
    assert ring["ring_pushed_records"] == ring["dispatch_records"]
    assert ring["journal_records"] == \
        by_mode[("counters", "off")]["journal_records"] == 0
    assert by_mode[("events", "on")]["journal_records"] == \
        by_mode[("events", "off")]["journal_records"]
    out = tmp_path / "replay.json"
    subprocess.run(
        [sys.executable, str(repo / "benchmarks" / "health_replay.py"),
         "--out", str(out)],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert json.loads(out.read_text()) == committed


def test_save_results_roundtrip(tmp_path):
    import json

    payload = {"platform": "cpu", "n_devices": 8, "allreduce": []}
    path = micro.save_results(payload, outdir=str(tmp_path))
    assert os.path.basename(path).startswith("micro_cpu_8dev_")
    with open(path) as f:
        assert json.load(f) == payload


def test_fit_alpha_beta_exact_line():
    # a perfect alpha-beta line fits back exactly: 2 us + bytes at
    # 1 GB/s (== 1000 bytes/us)
    pts = [(b, 2.0 + b / 1e3) for b in (1e3, 1e4, 1e5, 1e6)]
    alpha, bw = micro.fit_alpha_beta(pts)
    assert abs(alpha - 2.0) < 1e-6
    assert abs(bw - 1.0) < 1e-6


def test_fit_alpha_beta_clamps_degenerate():
    # a tiny sweep can fit a negative intercept / non-positive slope;
    # the result must still be loadable (alpha >= 0, bw > 0)
    alpha, bw = micro.fit_alpha_beta([(16.0, 5.0), (32.0, 4.0)])
    assert alpha >= 0 and bw > 0


def test_measured_ring_crossover_interpolates():
    rows = [
        {"size_mb": 0.1, "butterfly_us": 10.0, "ring_us": 20.0,
         "ring_speedup": 0.5},
        {"size_mb": 1.0, "butterfly_us": 40.0, "ring_us": 30.0,
         "ring_speedup": 1.33},
    ]
    x = micro.measured_ring_crossover(rows)
    # delta goes -10 -> +10 over 0.1..1 MB: crossover at the midpoint
    assert x is not None and 0.5e6 < x < 0.6e6
    # one-device sweeps (speedup None) yield no crossover
    assert micro.measured_ring_crossover(
        [{"size_mb": 1.0, "butterfly_us": 1, "ring_us": 1,
          "ring_speedup": None}]) is None


def test_provenance_block_schema():
    # every --save payload carries the self-description the autotune
    # fitter needs: versions, topology, and the config stamp
    prov = micro.provenance_block("cpu", 8)
    assert set(prov) >= {"jax", "jaxlib", "platform", "n_devices",
                         "topology", "config_stamp"}
    assert prov["platform"] == "cpu" and prov["n_devices"] == 8
    assert len(prov["config_stamp"]) == 12
    int(prov["config_stamp"], 16)  # hex content stamp
    assert "x" in prov["topology"]
    assert micro.MICRO_SCHEMA == "mpx-micro-bench/1"


def test_cost_calibrate_schema_loads_verbatim(tmp_path):
    # the --cost-calibrate output IS the tuning file: build it from
    # real (tiny) sweep rows, save it, and load it through BOTH
    # consumers — the cost-model loader (superset schema accepted) and
    # the config tuning layer — schema drift fails here, fast
    from mpi4jax_tpu.analysis import costmodel
    from mpi4jax_tpu.autotune import validate_tuning_dict

    comm = _world_comm()
    pp = micro.bench_sendrecv_ring(comm, sizes_kb=[0.004, 4], iters=2)
    al = micro.bench_allreduce_algos(comm, sizes_mb=[0.0001], iters=2)
    cm = micro.build_cost_model("cpu", comm.Get_size(), pp, al)
    assert cm["schema"] == costmodel.TUNING_SCHEMA
    assert set(cm["links"]) == {"ici", "dcn"}
    assert cm["provenance"]["n_devices"] == comm.Get_size()
    validate_tuning_dict(cm)  # loads whole as an MPI4JAX_TPU_TUNING file
    if "measured" in cm:
        # the measured crossover doubles as the tuned knob value
        assert cm["tuned"]["ring_crossover_bytes"] == \
            cm["measured"]["ring_crossover_bytes"]
    tf = mpx.load_tuning(cm)
    try:
        assert tf.has_links()
    finally:
        mpx.load_tuning(None)
    path = micro.save_cost_model(cm, outdir=str(tmp_path))
    assert os.path.basename(path).startswith("cost_model_cpu_")
    model = costmodel.model_from_file(path)
    assert model.params["links"]["ici"]["gb_per_s"] > 0
    assert model.source == path
    # and the env-flag route resolves the same file
    saved = os.environ.get("MPI4JAX_TPU_COST_MODEL")
    os.environ["MPI4JAX_TPU_COST_MODEL"] = path
    try:
        loaded = costmodel.load_model(None)
        assert loaded.params["links"]["ici"] == \
            model.params["links"]["ici"]
    finally:
        if saved is None:
            os.environ.pop("MPI4JAX_TPU_COST_MODEL", None)
        else:
            os.environ["MPI4JAX_TPU_COST_MODEL"] = saved
