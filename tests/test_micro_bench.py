"""Smoke test for benchmarks/micro.py — it must keep producing numbers.

VERDICT r2: micro.py had never been executed by CI, so it could silently
rot.  Run both sweeps at tiny sizes on the test mesh and check the output
schema matches what benchmarks/results/*.json commits.
"""

import os
import sys

import jax

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "benchmarks")
)

import micro  # noqa: E402

import mpi4jax_tpu as mpx  # noqa: E402


def _world_comm():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def test_bench_allreduce_schema():
    comm = _world_comm()
    rows = micro.bench_allreduce(comm, sizes_mb=[0.0001], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["time_us"] > 0
    # tiny payloads round the bandwidth to 0.0 — only presence is asserted
    assert (r["bus_gb_s"] is None) == (comm.Get_size() == 1)


def test_bench_sendrecv_schema():
    comm = _world_comm()
    rows = micro.bench_sendrecv_ring(comm, sizes_kb=[0.004], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["hop_us"] > 0
    assert (r["link_gb_s"] is None) == (comm.Get_size() == 1)


def test_bench_prod_and_split_schema():
    comm = _world_comm()
    rows = micro.bench_prod_and_split(comm, sizes_mb=[0.0001], iters=2)
    assert len(rows) == 1
    r = rows[0]
    assert r["prod_us"] > 0
    assert (r["prod_split_us"] is None) == (comm.Get_size() == 1)
    if r["prod_split_us"] is not None:
        assert r["prod_split_us"] > 0


def test_bench_allreduce_algos_schema():
    # force-compiles BOTH CollectivePermute algorithms (butterfly + ring)
    # at a tiny size: a lowering regression in either fails here, fast
    comm = _world_comm()
    saved = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    rows = micro.bench_allreduce_algos(comm, sizes_mb=[0.0001], iters=2)
    assert os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO") == saved  # restored
    assert len(rows) == 1
    r = rows[0]
    assert r["butterfly_us"] > 0 and r["ring_us"] > 0
    assert (r["ring_speedup"] is None) == (comm.Get_size() == 1)


def test_bench_fusion_schema():
    # compiles the fused AND unfused programs at a tiny size: a deferral
    # or packing regression in the fusion layer fails here, fast
    comm = _world_comm()
    saved = os.environ.get("MPI4JAX_TPU_FUSION")
    rows = micro.bench_fusion(comm, counts=(4,), size_kb=0.02, iters=1)
    assert os.environ.get("MPI4JAX_TPU_FUSION") == saved  # restored
    assert len(rows) == 1
    r = rows[0]
    assert r["count"] == 4
    assert r["unfused_us_per_op"] > 0 and r["fused_us_per_op"] > 0
    assert r["fused_speedup"] > 0


def test_bench_overlap_schema():
    comm = _world_comm()
    rows = micro.bench_overlap(comm, sizes_mb=[0.0001], iters=2,
                               compute_dim=8)
    assert len(rows) == 1
    r = rows[0]
    assert r["monolithic_us"] > 0 and r["overlap_us"] > 0
    assert r["chunks"] >= 1 and r["overlap_speedup"] > 0


def test_bench_hierarchy_schema():
    # compiles the flat ring AND the forced two-level lowering under a
    # faked 2x4 host topology at a tiny size: a hierarchy regression in
    # either fails here, fast; a topology spec that does not cover the
    # mesh is skipped, not an error (docs/topology.md)
    comm = _world_comm()
    saved_topo = os.environ.get("MPI4JAX_TPU_TOPOLOGY")
    saved_algo = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    rows = micro.bench_hierarchy(comm, sizes_mb=[0.0001],
                                 topologies=("2x4", "3x9"), iters=2)
    assert os.environ.get("MPI4JAX_TPU_TOPOLOGY") == saved_topo  # restored
    assert os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO") == saved_algo
    assert len(rows) == 1  # 3x9 covers 27 ranks, not this mesh: skipped
    r = rows[0]
    assert r["topology"] == "2x4"  # the topology stamp --save commits
    assert r["flat_us"] > 0 and r["hier_us"] > 0
    assert (r["hier_speedup"] is None) == (comm.Get_size() == 1)


def test_bench_dispatch_schema():
    # compiles all three execution surfaces — eager one-op, spmd, and
    # the mpx.compile-pinned artifact — for the same allreduce at a tiny
    # size: a pinning or dispatch-path regression fails here, fast
    comm = _world_comm()
    rows = micro.bench_dispatch(comm, sizes_kb=[0.004], iters=3)
    assert len(rows) == 1
    r = rows[0]
    assert r["eager_us"] > 0 and r["spmd_us"] > 0 and r["pinned_us"] > 0
    assert r["pinned_vs_spmd"] is not None and r["pinned_vs_spmd"] > 0
    # the sweep pinned at least one program this process
    assert mpx.cache_stats()["aot"]["pins"] >= 1


def test_bench_dispatch_unroll_schema():
    # compiles the same one-allreduce step pinned at two megastep trip
    # counts (mpx.compile(fn, ..., unroll=N)) — a megastep lowering or
    # amortization-math regression fails here, fast (docs/aot.md
    # "Megastep execution"); the full 1/8 amortization assert at
    # unroll=64 lives in the CI aot lane against the saved sweep
    comm = _world_comm()
    du = micro.bench_dispatch_unroll(comm, unrolls=(1, 4), size_kb=0.004,
                                     iters=3)
    assert set(du) == {"size_kb", "onchip_per_step_us", "rows"}
    assert du["onchip_per_step_us"] >= 0
    assert [r["unroll"] for r in du["rows"]] == [1, 4]
    for r in du["rows"]:
        assert r["megastep_us"] > 0 and r["per_step_us"] > 0
        assert r["per_step_host_us"] >= 0
        assert isinstance(r["fast_path"], bool)
    # amortization direction: per-step host cost must not grow with N
    assert (du["rows"][1]["per_step_host_us"]
            <= du["rows"][0]["per_step_host_us"] + 1e-9)
    assert mpx.cache_stats()["aot"]["pins"] >= 2


def test_save_results_roundtrip(tmp_path):
    import json

    payload = {"platform": "cpu", "n_devices": 8, "allreduce": []}
    path = micro.save_results(payload, outdir=str(tmp_path))
    assert os.path.basename(path).startswith("micro_cpu_8dev_")
    with open(path) as f:
        assert json.load(f) == payload
