"""Long-context attention built on the primitives (SURVEY.md §5: the
framework must make ring/Ulysses sequence parallelism expressible on the op
set; mpi4jax_tpu/attention.py is the first-class implementation).

Both schemes are exact, so the acceptance test is equality with full
single-device attention on the gathered sequence.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

from long_context_attention import (  # noqa: E402
    reference_attention,
    ring_attention,
    ulysses_attention,
)

SIZE = 8
B, T_LOC, H, D = 2, 16, 8, 32


def _data(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (SIZE, B, T_LOC, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _global(x):
    """(SIZE, B, T_loc, H, D) stacked shards -> (B, T_global, H, D)."""
    x = np.asarray(x)
    return np.concatenate(list(x), axis=1)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("scheme", [ring_attention, ulysses_attention])
def test_matches_single_device(scheme, causal):
    comm = mpx.get_default_comm()
    q, k, v = _data()

    @mpx.spmd
    def f(q, k, v):
        return scheme(q, k, v, comm=comm, causal=causal)

    out = _global(f(q, k, v))
    expected = np.asarray(
        reference_attention(
            jnp.asarray(_global(q)), jnp.asarray(_global(k)),
            jnp.asarray(_global(v)), causal=causal,
        )
    )
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable():
    """Sequence parallelism composes with autodiff: grad through the ring's
    sendrecvs matches grad through full attention."""
    comm = mpx.get_default_comm()
    q, k, v = _data(1)

    @mpx.spmd
    def loss_sharded(q, k, v):
        out = ring_attention(q, k, v, comm=comm, causal=True)
        l, _ = mpx.allreduce((out**2).sum(), op=mpx.SUM)
        return mpx.varying(l)

    def loss_full(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return (out**2).sum()

    # the allreduced loss is replicated per rank, so summing the stacked
    # outputs counts it SIZE times — divide back out
    g_sharded = jax.grad(lambda q: jnp.sum(loss_sharded(q, k, v)) / SIZE)(q)
    g_full = jax.grad(
        lambda qg: loss_full(qg, jnp.asarray(_global(k)), jnp.asarray(_global(v)))
    )(jnp.asarray(_global(q)))
    np.testing.assert_allclose(
        _global(g_sharded), np.asarray(g_full), rtol=2e-3, atol=2e-4
    )


def test_ulysses_rejects_bad_head_count():
    comm = mpx.get_default_comm()
    q = jnp.zeros((SIZE, B, T_LOC, SIZE - 1, D))

    @mpx.spmd
    def f(q):
        return ulysses_attention(q, q, q, comm=comm)

    with pytest.raises(ValueError, match="divisible"):
        f(q)


def test_ulysses_attention_differentiable():
    """ulysses runs its local attention through the flash kernel; its
    blockwise custom VJP (plus the alltoall transpose rules) must keep
    jax.grad working and matching the single-device gradient."""
    comm = mpx.get_default_comm()
    q, k, v = _data(3)

    def loss_sharded(q, k, v):
        @mpx.spmd
        def f(q, k, v):
            out = ulysses_attention(q, k, v, comm=comm, causal=True)
            return jnp.sum(out**2)

        return f(q, k, v)

    def loss_full(qg, kg, vg):
        return jnp.sum(reference_attention(qg, kg, vg, causal=True) ** 2)

    # each rank's scalar here is a rank-local partial sum (no allreduce in
    # the loss), so summing the stacked outputs IS the global loss
    g_sharded = jax.grad(lambda q: jnp.sum(loss_sharded(q, k, v)))(q)
    g_full = jax.grad(
        lambda qg: loss_full(qg, jnp.asarray(_global(k)), jnp.asarray(_global(v)))
    )(jnp.asarray(_global(q)))
    np.testing.assert_allclose(
        _global(g_sharded), np.asarray(g_full), rtol=2e-3, atol=2e-4
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_memory_efficient_grad_matches_plain_ad(causal):
    """The memory-efficient ring backward (rank-local residuals only; K/V
    re-rotated during the backward with dK/dV accumulators traveling the
    ring) must match plain reverse-mode AD through the forward — for all
    three inputs, causal and not."""
    comm = mpx.get_default_comm()
    q, k, v = _data(7)

    def loss(q, k, v, me):
        @mpx.spmd
        def f(q, k, v):
            out = ring_attention(q, k, v, comm=comm, causal=causal,
                                 memory_efficient_grad=me)
            l, _ = mpx.allreduce((out**2).sum(), op=mpx.SUM)
            return mpx.varying(l)

        return jnp.sum(f(q, k, v)) / SIZE

    g_me = jax.grad(lambda *a: loss(*a, True), (0, 1, 2))(q, k, v)
    g_ad = jax.grad(lambda *a: loss(*a, False), (0, 1, 2))(q, k, v)
    for wrt in (0, 1, 2):
        np.testing.assert_allclose(
            np.asarray(g_me[wrt]), np.asarray(g_ad[wrt]),
            rtol=1e-4, atol=1e-5,
            err_msg=f"d{'qkv'[wrt]} (causal={causal})",
        )


def test_ring_memory_efficient_grad_bf16():
    """bf16 shards through the memory-efficient backward: grads come back
    in the input dtype, finite, and within bf16 tolerance of a TRUE f32
    gradient (computed from the f32 inputs, so a systematic bf16 error
    shared by both backward paths cannot hide)."""
    comm = mpx.get_default_comm()
    q32, k32, v32 = _data(9)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))

    def loss(q, k, v, me):
        @mpx.spmd
        def f(q, k, v):
            out = ring_attention(q, k, v, comm=comm, causal=True,
                                 memory_efficient_grad=me)
            l, _ = mpx.allreduce(jnp.sum(out.astype(jnp.float32) ** 2),
                                 op=mpx.SUM)
            return mpx.varying(l)

        return jnp.sum(f(q, k, v)) / SIZE

    g_me = jax.grad(lambda q: loss(q, k, v, True))(q)
    g_f32 = jax.grad(lambda q: loss(q, k32, v32, False))(q32)
    assert g_me.dtype == jnp.bfloat16
    a = np.asarray(g_me).astype(np.float32)
    e = np.asarray(g_f32)
    assert np.isfinite(a).all()
    np.testing.assert_allclose(a, e, rtol=0.1, atol=0.05)


def test_ring_memory_efficient_grad_uses_less_memory():
    """The point of the custom VJP: XLA's own memory analysis must show the
    memory-efficient backward allocating well under plain AD's residuals
    (measured ~15 vs ~51 MiB temp at T_local=512 on the 8-rank mesh; the
    plain path pins every rotated K/V block plus per-step merge
    accumulators, O(n * T_local), while the custom VJP re-communicates)."""
    comm = mpx.get_default_comm()
    n, b, t_loc, h, d = SIZE, 1, 512, 2, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (
        jax.random.normal(kk, (n, b, t_loc, h, d), jnp.float32) for kk in ks
    )

    def make_grad(me):
        def loss(q, k, v):
            @mpx.spmd
            def f(q, k, v):
                out = ring_attention(q, k, v, comm=comm, causal=True,
                                     memory_efficient_grad=me)
                l, _ = mpx.allreduce((out**2).sum(), op=mpx.SUM)
                return mpx.varying(l)

            return jnp.sum(f(q, k, v))

        return jax.jit(jax.grad(loss, (0, 1, 2)))

    temps = {}
    for me in (False, True):
        ma = make_grad(me).lower(q, k, v).compile().memory_analysis()
        if ma is None:  # jax documents None for unsupported backends
            pytest.skip("memory_analysis unavailable on this backend")
        temps[me] = ma.temp_size_in_bytes
    assert temps[True] < temps[False] / 2, (
        f"memory-efficient backward lost its advantage: "
        f"{temps[True]/2**20:.1f} vs {temps[False]/2**20:.1f} MiB temp"
    )
