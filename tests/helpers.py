"""Shared test helpers: the world comm/size and global-array builders.

Mirrors the reference's module-level ``comm/rank/size`` globals
(ref tests/collective_ops/test_allreduce.py:8-10), adapted to the SPMD
model: ``SIZE`` virtual devices, global arrays carry a leading rank axis.
"""

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as mpx

COMM = None
SIZE = None


def world():
    global COMM, SIZE
    if COMM is None:
        COMM = mpx.get_default_comm()
        SIZE = COMM.Get_size()
    return COMM, SIZE


def per_rank(fn_of_rank, *, dtype=jnp.float32):
    """Build a global array where global[r] = fn_of_rank(r)."""
    _, size = world()
    return jnp.stack([jnp.asarray(fn_of_rank(r), dtype=dtype) for r in range(size)])


def ranks_arange(shape=(), dtype=jnp.float32):
    """global[r] = full(shape, r) — the README-style input."""
    _, size = world()
    return per_rank(lambda r: np.full(shape, r), dtype=dtype)
