"""Resilience-layer tests (docs/resilience.md).

Covers the four pieces of mpi4jax_tpu/resilience/:

- fault-spec parser round-trips and host-side trigger semantics
  (``after=N`` counting, rank filtering, delay/die/corrupt actions);
- collective-watchdog registry (FIFO aliasing, expiry, the monitor
  thread, diagnostic format) and its in-graph arm/disarm bracket;
- retry_with_backoff (success after refusals, deadline error clarity,
  jitter envelope, giveup escape) and its ``init_distributed`` wiring;
- numeric guards, including the zero-cost-when-off HLO pin.

The pure-Python modules are loaded under a private package name
(``_load_isolated`` below) so the parser/registry/retry tests run even
where the installed JAX is below the package's hard floor and
``import mpi4jax_tpu`` refuses; the JAX-integration half skips there.

Fatal paths (die faults, numeric aborts, the hung-2-process watchdog
kill) are subprocess-isolated, mirroring tests/test_native.py's
abort test (ref test_common.py:60-88).  The whole module carries the
``faults`` marker: CI runs it as a dedicated lane with the native hooks
library built (docs/resilience.md "Testing").
"""

import importlib
import os
import pathlib
import re
import subprocess
import sys
import textwrap
import time
import types
import warnings

import pytest

pytestmark = pytest.mark.faults

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

try:
    import mpi4jax_tpu as _mpx_probe  # noqa: F401

    HAS_MPX = True
except RuntimeError:  # JAX below the package floor (utils/jax_compat.py)
    HAS_MPX = False

needs_mpx = pytest.mark.skipif(
    not HAS_MPX, reason="mpi4jax_tpu import refused (JAX below hard floor)"
)

_ISO_NAME = "_mpx_resilience_iso"


def _load_isolated():
    """Load the pure-Python resilience modules under a private package name.

    Bypasses ``mpi4jax_tpu/__init__.py`` (whose JAX-floor check refuses to
    import on old JAX) while preserving package context, so the modules'
    relative imports (``..utils.config``, ``.faultinject``) resolve inside
    the private namespace.  Also gives the tests module state isolated from
    any real ``mpi4jax_tpu`` import in the same process.
    """
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "resilience", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "resilience.faultinject",
        "resilience.retry",
        "resilience.watchdog",
        "resilience.runtime",
        "parallel.mesh",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
fi = ISO.resilience.faultinject
wd = ISO.resilience.watchdog
rt = ISO.resilience.runtime
retry_mod = ISO.resilience.retry
config = ISO.utils.config


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Every test starts and ends with no overrides, no trigger counts, and
    no resilience environment variables."""
    rt.reset_overrides()
    fi.reset_fault_state()
    saved = {
        k: os.environ.pop(k, None)
        for k in (
            "MPI4JAX_TPU_WATCHDOG_TIMEOUT",
            "MPI4JAX_TPU_FAULT_SPEC",
            "MPI4JAX_TPU_CHECK_NUMERICS",
            "MPI4JAX_TPU_TOPOLOGY",
        )
    }
    yield
    rt.reset_overrides()
    fi.reset_fault_state()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# fault-spec parser
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "spec",
    [
        "delay:rank=1:op=allreduce:after=3:secs=2",
        "die:rank=0:op=barrier:after=1",
        "hang:rank=3:op=allreduce:after=5",
        "hang",
        "preempt:rank=3:after=4:grace=2",
        "preempt:rank=3:op=allreduce:after=4",
        "preempt",
        "corrupt:nan:rank=2:op=allreduce",
        "corrupt:inf:op=bcast",
        "delay:secs=0.5",
        "die",
        "delay:rank=1:op=allreduce:after=3:secs=2;"
        "die:rank=0:op=barrier:after=1;hang:rank=3:op=allreduce;"
        "preempt:rank=2:after=1:grace=5;"
        "corrupt:nan:rank=2:op=allreduce",
    ],
)
def test_fault_spec_round_trips(spec):
    """parse -> canonical -> parse is a fixed point for every verb."""
    clauses = fi.parse_fault_spec(spec)
    canon = fi.canonical_spec(clauses)
    assert fi.parse_fault_spec(canon) == clauses
    assert fi.canonical_spec(fi.parse_fault_spec(canon)) == canon


def test_fault_spec_field_semantics():
    (c,) = fi.parse_fault_spec("delay:rank=1:op=AllReduce:after=3:secs=2")
    assert (c.verb, c.rank, c.op, c.after, c.secs) == (
        "delay", 1, "allreduce", 3, 2.0,  # op is lowercased
    )
    (c,) = fi.parse_fault_spec("corrupt")
    assert (c.verb, c.mode, c.rank, c.op) == ("corrupt", "nan", None, None)
    assert c.matches_op("barrier") and c.matches_op("allreduce")
    (c,) = fi.parse_fault_spec("corrupt:inf:op=bcast")
    assert c.mode == "inf"
    assert c.matches_op("bcast") and not c.matches_op("allreduce")
    assert fi.parse_fault_spec("") == ()
    assert fi.parse_fault_spec("  ; ;") == ()


@pytest.mark.parametrize(
    "bad",
    [
        "explode:rank=1",              # unknown verb
        "delay:when=now",              # unknown key
        "delay:nan",                   # bare mode on a non-corrupt verb
        "corrupt:frob",                # unknown bare mode
        "delay:rank=one",              # non-integer rank
        "delay:secs=fast",             # non-float secs
        "die:secs=2",                  # secs on a non-delay verb
        "hang:secs=2",                 # hang is forever; secs is delay-only
        "hang:nan",                    # bare mode on a non-corrupt verb
        "die:grace=2",                 # grace is preempt-only
        "preempt:secs=2",              # a notice does not sleep
        "preempt:grace=0",             # grace must be positive
        "preempt:nan",                 # bare mode on a non-corrupt verb
        "delay:rank=1:rank=2",         # duplicate key
        "delay:after=-1",              # negative after
        "delay:secs=-0.5",             # negative secs
        "delay::secs=1",               # empty field
    ],
)
def test_fault_spec_rejects_bad_clauses(bad):
    with pytest.raises(ValueError, match="fault spec clause"):
        fi.parse_fault_spec(bad)


# ---------------------------------------------------------------------------
# host-side trigger semantics (probe_host)
# ---------------------------------------------------------------------------


def test_corrupt_after_counts_per_rank():
    """``after=N``: the first N matching calls per rank run clean, every
    later one fires — and rank counters are independent."""
    (c,) = fi.parse_fault_spec("corrupt:nan:after=2")
    indexed = ((0, c),)
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0  # call 1: clean
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0  # call 2: clean
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 1  # call 3: fires
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 1  # keeps firing
    # rank 1 has its own counter, still in the clean window
    assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0
    fi.reset_fault_state()
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0  # counters forgotten


def test_rank_filter_and_corrupt_bitmask():
    clauses = fi.parse_fault_spec("corrupt:nan:rank=1;corrupt:inf:rank=2")
    indexed = tuple(enumerate(clauses))
    assert fi.probe_host(indexed, "MPI_Bcast", 0) == 0      # matches neither
    assert fi.probe_host(indexed, "MPI_Bcast", 1) == 0b01   # clause bit 0
    assert fi.probe_host(indexed, "MPI_Bcast", 2) == 0b10   # clause bit 1


def test_delay_sleeps_only_after_threshold():
    (c,) = fi.parse_fault_spec("delay:rank=0:after=1:secs=0.2")
    indexed = ((0, c),)
    t0 = time.perf_counter()
    fi.probe_host(indexed, "MPI_Allreduce", 0)  # call 1: clean window
    clean = time.perf_counter() - t0
    t0 = time.perf_counter()
    fi.probe_host(indexed, "MPI_Allreduce", 0)  # call 2: sleeps
    fired = time.perf_counter() - t0
    assert clean < 0.15, clean
    assert fired >= 0.15, fired


def test_die_exits_process_with_code_13(monkeypatch):
    calls = []
    monkeypatch.setattr(fi.os, "_exit", lambda code: calls.append(code))
    (c,) = fi.parse_fault_spec("die:rank=3")
    fi.probe_host(((0, c),), "MPI_Barrier", 2)   # wrong rank: survives
    assert calls == []
    fi.probe_host(((0, c),), "MPI_Barrier", 3)
    assert calls == [13]


# ---------------------------------------------------------------------------
# host-scoped faults (PR 16 satellite: die-host / host=)
# ---------------------------------------------------------------------------


def test_die_host_shorthand_parses_to_the_canonical_long_form():
    (c,) = fi.parse_fault_spec("die-host:1@3")
    assert (c.verb, c.host, c.rank, c.after) == ("die", 1, None, 3)
    assert c.canonical() == "die:host=1:after=3"
    # round-trips through the long form
    assert fi.parse_fault_spec(c.canonical()) == (c,)
    # op# optional (fire immediately)
    (c0,) = fi.parse_fault_spec("die-host:0")
    assert (c0.host, c0.after) == (0, 0)
    # host= composes with other verbs and keys
    (cd,) = fi.parse_fault_spec("delay:host=1:op=allreduce:secs=0.5")
    assert (cd.verb, cd.host, cd.op, cd.secs) == (
        "delay", 1, "allreduce", 0.5)
    assert cd.canonical() == "delay:host=1:op=allreduce:secs=0.5"


@pytest.mark.parametrize("bad", [
    "die-host:",                 # missing host
    "die-host:one",              # non-integer host
    "die-host:1@x",              # non-integer op#
    "die-host:-1",               # negative host
    "die-host:1@2:after=3",      # extra fields on the shorthand
    "die:host=-2",               # negative host in long form
    "die:rank=1:host=2",         # rank and host are mutually exclusive
])
def test_host_fault_rejects_bad_clauses(bad):
    with pytest.raises(ValueError, match="fault spec clause"):
        fi.parse_fault_spec(bad)


def test_die_host_kills_every_rank_of_the_host(monkeypatch):
    """With MPI4JAX_TPU_TOPOLOGY=2x4, die-host:1 fires for ranks 4..7
    and no others — the host-row kill the drills script."""
    calls = []
    monkeypatch.setattr(fi.os, "_exit", lambda code: calls.append(code))
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    (c,) = fi.parse_fault_spec("die-host:1")
    indexed = ((0, c),)
    for r in (0, 1, 2, 3):
        fi.probe_host(indexed, "MPI_Barrier", r)
    assert calls == []
    for r in (4, 5, 6, 7):
        fi.probe_host(indexed, "MPI_Barrier", r)
    assert calls == [13, 13, 13, 13]
    # a rank past the spec's coverage matches nothing
    fi.probe_host(indexed, "MPI_Barrier", 11)
    assert len(calls) == 4


def test_die_host_after_counts_per_rank(monkeypatch):
    calls = []
    monkeypatch.setattr(fi.os, "_exit", lambda code: calls.append(code))
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "4,4"
    (c,) = fi.parse_fault_spec("die-host:0@2")
    indexed = ((0, c),)
    assert fi.probe_host(indexed, "MPI_Allreduce", 2) == 0  # clean 1
    assert fi.probe_host(indexed, "MPI_Allreduce", 2) == 0  # clean 2
    assert calls == []
    fi.probe_host(indexed, "MPI_Allreduce", 2)              # call 3 fires
    assert calls == [13]


def test_host_fault_without_topology_matches_nothing_and_warns_once():
    (c,) = fi.parse_fault_spec("corrupt:nan:host=0")
    indexed = ((0, c),)
    with pytest.warns(RuntimeWarning, match="MPI4JAX_TPU_TOPOLOGY"):
        assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0
    # warned once; later probes stay silent (and still match nothing)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0
    # reset re-arms the warning (test isolation)
    fi.reset_fault_state()
    with pytest.warns(RuntimeWarning):
        fi.probe_host(indexed, "MPI_Allreduce", 0)


# ---------------------------------------------------------------------------
# watchdog registry + monitor
# ---------------------------------------------------------------------------


def test_watchdog_registry_fifo_and_snapshot():
    """Re-arming under one call id (a trace site inside fori_loop) must
    stack FIFO, not clobber — same aliasing story as the native hooks."""
    reg = wd._Registry(on_timeout=lambda entries, expired: None)
    reg.arm("MPI_Allreduce", "aabbccdd", 0, "('i',)", timeout=60.0)
    reg.arm("MPI_Allreduce", "aabbccdd", 0, "('i',)", timeout=60.0)
    snap = reg.snapshot()
    assert len(snap) == 2
    e = snap[0]
    assert e["opname"] == "MPI_Allreduce" and e["call_id"] == "aabbccdd"
    assert e["rank"] == 0 and e["axes"] == "('i',)"
    assert 0 <= e["elapsed"] < 60 and e["timeout"] == 60.0
    assert reg.check_expired() is None
    reg.disarm("aabbccdd", 0)
    assert len(reg.snapshot()) == 1
    reg.disarm("aabbccdd", 0)
    assert reg.empty()
    reg.disarm("aabbccdd", 0)  # spurious disarm is a no-op, not an error
    assert reg.empty()


def test_watchdog_expiry_with_injected_clock():
    now = [100.0]
    reg = wd._Registry(on_timeout=lambda entries, expired: None,
                       clock=lambda: now[0])
    reg.arm("MPI_Gather", "12345678", 1, "('i',)", timeout=5.0)
    assert reg.check_expired() is None
    now[0] += 4.9
    assert reg.check_expired() is None
    now[0] += 0.2
    expired = reg.check_expired()
    assert expired is not None and expired["opname"] == "MPI_Gather"
    assert expired["elapsed"] == pytest.approx(5.1)


def test_watchdog_monitor_thread_fires():
    fired = []
    reg = wd._Registry(on_timeout=lambda entries, expired: fired.append(
        (entries, expired)))
    reg.arm("MPI_Allreduce", "deadbeef", 0, "('i',)", timeout=0.15)
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.05)
    assert fired, "monitor thread never fired on an expired collective"
    entries, expired = fired[0]
    assert expired["opname"] == "MPI_Allreduce"
    assert expired["elapsed"] > 0.15
    assert any(e["call_id"] == "deadbeef" for e in entries)


def test_watchdog_timeout_diagnostic_format(monkeypatch):
    """The default on_timeout dumps every in-flight op then dies through the
    host fatal path, naming the expired op/call/axes/timeout."""
    lines, fatal = [], []
    fake_native = types.ModuleType(f"{_ISO_NAME}.native")
    fake_native.host_line = lambda rank, text: lines.append((rank, text))
    fake_native.host_fatal = lambda rank, text: fatal.append((rank, text))
    monkeypatch.setitem(sys.modules, f"{_ISO_NAME}.native", fake_native)
    monkeypatch.setattr(ISO, "native", fake_native, raising=False)

    entries = [
        dict(opname="MPI_Allreduce", call_id="aabbccdd", rank=0,
             axes="('i',)", elapsed=6.01, timeout=5.0),
        dict(opname="MPI_Barrier", call_id="11223344", rank=0,
             axes="('i',)", elapsed=1.5, timeout=5.0),
    ]
    wd._default_on_timeout(entries, entries[0])
    assert len(lines) == 2
    assert "WATCHDOG | in-flight: MPI_Allreduce (call aabbccdd" in lines[0][1]
    assert "elapsed 6.01s" in lines[0][1]
    assert len(fatal) == 1
    assert ("collective watchdog: MPI_Allreduce exceeded 5s "
            "(call aabbccdd, axes=('i',))") in fatal[0][1]


# ---------------------------------------------------------------------------
# retry_with_backoff
# ---------------------------------------------------------------------------


class _Flaky:
    def __init__(self, refusals, exc=ConnectionError):
        self.left = refusals
        self.exc = exc
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.left > 0:
            self.left -= 1
            raise self.exc(f"refused ({self.calls})")
        return "connected"


def test_retry_succeeds_after_refusals_with_exponential_envelope():
    sleeps = []
    now = [0.0]

    def sleep(s):
        sleeps.append(s)
        now[0] += s

    fn = _Flaky(4)
    out = retry_mod.retry_with_backoff(
        fn, what="test rendezvous", deadline=300.0, base_delay=1.0,
        max_delay=4.0, jitter=False, sleep=sleep, clock=lambda: now[0],
    )
    assert out == "connected" and fn.calls == 5
    # deterministic (jitter off) doubling, capped at max_delay
    assert sleeps == [1.0, 2.0, 4.0, 4.0]


def test_retry_jitter_draws_from_capped_envelope(monkeypatch):
    draws = []
    monkeypatch.setattr(
        retry_mod.random, "uniform",
        lambda a, b: draws.append((a, b)) or b,
    )
    now = [0.0]
    retry_mod.retry_with_backoff(
        _Flaky(3), deadline=300.0, base_delay=1.0, max_delay=4.0,
        sleep=lambda s: None, clock=lambda: now[0],
    )
    # full jitter: U(0, min(base * 2^n, max_delay))
    assert draws == [(0, 1.0), (0, 2.0), (0, 4.0)]


def test_retry_deadline_gives_clear_error():
    now = [0.0]

    def clock():
        return now[0]

    def sleep(s):
        now[0] += s

    fn = _Flaky(10**6)
    with pytest.raises(RuntimeError) as exc_info:
        retry_mod.retry_with_backoff(
            fn, what="coordinator connection (host:1234)", deadline=50.0,
            base_delay=10.0, max_delay=100.0, jitter=False,
            sleep=sleep, clock=clock,
        )
    msg = str(exc_info.value)
    assert "coordinator connection (host:1234)" in msg
    assert "attempt" in msg and "deadline 50s" in msg
    assert "ConnectionError" in msg
    assert isinstance(exc_info.value.__cause__, ConnectionError)
    # the sleep before the last attempt was clamped: failure lands at the
    # promised time, not one full backoff step past it
    assert now[0] == pytest.approx(50.0)


def test_retry_nonretryable_and_giveup_escape_immediately():
    fn = _Flaky(5, exc=ValueError)
    with pytest.raises(ValueError):
        retry_mod.retry_with_backoff(fn, sleep=lambda s: None)
    assert fn.calls == 1

    fn = _Flaky(5, exc=RuntimeError)
    with pytest.raises(RuntimeError, match="refused"):
        retry_mod.retry_with_backoff(
            fn, sleep=lambda s: None, giveup=lambda e: "refused" in str(e),
        )
    assert fn.calls == 1


def test_retry_rejects_nonpositive_deadline():
    with pytest.raises(ValueError, match="deadline"):
        retry_mod.retry_with_backoff(lambda: None, deadline=0)


def test_backoff_delay_pure_jitter_ceiling():
    """The pure envelope (PR 16 satellite): exponential growth, an
    explicit saturating cap, and overflow safety at absurd attempt
    counts."""
    assert retry_mod.backoff_delay(1) == 1.0
    assert retry_mod.backoff_delay(3) == 4.0
    assert retry_mod.backoff_delay(10) == 30.0          # capped
    assert retry_mod.backoff_delay(10_000) == 30.0      # still capped
    assert retry_mod.backoff_delay(
        2, base_delay=0.05, factor=3.0, max_delay=1.0) == pytest.approx(0.15)
    # base 0 = no backoff at all (and no inf * 0 NaN at huge attempts)
    assert retry_mod.backoff_delay(10_000, base_delay=0.0) == 0.0
    # factor 1 = constant
    assert retry_mod.backoff_delay(7, factor=1.0, base_delay=2.0) == 2.0


@pytest.mark.parametrize("kwargs", [
    {"attempt": 0},
    {"attempt": -3},
    {"base_delay": -1.0},
    {"factor": 0.5},
    {"max_delay": 0.0},
    {"max_delay": -2.0},
])
def test_backoff_delay_validates_parameters(kwargs):
    args = {"attempt": 1}
    args.update(kwargs)
    attempt = args.pop("attempt")
    with pytest.raises(ValueError):
        retry_mod.backoff_delay(attempt, **args)


def test_retry_validates_backoff_shape_before_first_sleep():
    calls = []

    def fn():
        calls.append(1)

    with pytest.raises(ValueError, match="factor"):
        retry_mod.retry_with_backoff(fn, factor=0.0, sleep=lambda s: None)
    assert calls == []  # rejected up front, fn never ran


def test_retry_jitter_sleeps_never_exceed_the_ceiling():
    """The jitter-bounds pin: with the real RNG, every sleep drawn over
    many failures stays within [0, backoff_delay(n)] — the stampede
    guarantee the elastic agreement reporters rely on."""
    sleeps = []
    now = [0.0]

    def sleep(s):
        sleeps.append(s)
        now[0] += 0.001   # virtual time: many attempts, tiny elapsed

    with pytest.raises(RuntimeError):
        retry_mod.retry_with_backoff(
            _Flaky(10**6), what="stampede", deadline=1.0,
            max_attempts=200, base_delay=0.01, max_delay=0.05,
            factor=2.0, sleep=sleep, clock=lambda: now[0],
        )
    assert len(sleeps) == 199
    for n, s in enumerate(sleeps, start=1):
        assert 0.0 <= s <= retry_mod.backoff_delay(
            n, base_delay=0.01, max_delay=0.05), (n, s)
    # the cap binds: late sleeps never exceed max_delay even though
    # 0.01 * 2^198 is astronomically larger
    assert max(sleeps) <= 0.05


def test_retry_exhaustion_reports_attempts_and_total_wait():
    """Satellite pin: both exhaustion errors carry the attempt count AND
    the total time spent sleeping between attempts."""
    now = [0.0]

    def sleep(s):
        now[0] += s

    with pytest.raises(RuntimeError) as exc_info:
        retry_mod.retry_with_backoff(
            _Flaky(10**6), what="agreement report", deadline=300.0,
            max_attempts=4, base_delay=1.0, jitter=False,
            sleep=sleep, clock=lambda: now[0],
        )
    msg = str(exc_info.value)
    # 3 sleeps of 1, 2, 4 seconds before the 4th failure
    assert "agreement report failed after 4 attempt(s)" in msg
    assert "7.0s of it waiting between attempts" in msg
    assert "max_attempts 4" in msg

    now[0] = 0.0
    with pytest.raises(RuntimeError) as exc_info:
        retry_mod.retry_with_backoff(
            _Flaky(10**6), what="agreement report", deadline=5.0,
            base_delay=2.0, jitter=False, sleep=sleep,
            clock=lambda: now[0],
        )
    msg = str(exc_info.value)
    assert "deadline 5s" in msg
    assert "waiting between attempts" in msg


# ---------------------------------------------------------------------------
# config resolution + runtime plan
# ---------------------------------------------------------------------------


def test_env_parsing():
    assert config.watchdog_timeout() is None            # unset
    os.environ["MPI4JAX_TPU_WATCHDOG_TIMEOUT"] = ""
    assert config.watchdog_timeout() is None            # empty
    os.environ["MPI4JAX_TPU_WATCHDOG_TIMEOUT"] = "0"
    assert config.watchdog_timeout() is None            # explicit off
    os.environ["MPI4JAX_TPU_WATCHDOG_TIMEOUT"] = "2.5"
    assert config.watchdog_timeout() == 2.5
    # nan would silently disable the watchdog while still instrumenting
    # every op (never-true comparisons); inf is meaningless as seconds
    for bad in ("-1", "soon", "nan", "inf"):
        os.environ["MPI4JAX_TPU_WATCHDOG_TIMEOUT"] = bad
        with pytest.raises(ValueError, match="MPI4JAX_TPU_WATCHDOG_TIMEOUT"):
            config.watchdog_timeout()

    assert config.check_numerics() is False
    os.environ["MPI4JAX_TPU_CHECK_NUMERICS"] = "1"
    assert config.check_numerics() is True

    assert config.fault_spec() == ""
    os.environ["MPI4JAX_TPU_FAULT_SPEC"] = "  die:rank=0  "
    assert config.fault_spec() == "die:rank=0"


def test_plan_default_off_and_per_op_clause_filter():
    assert rt.plan_for("allreduce") is None             # everything off
    rt.set_fault_spec("die:op=barrier;corrupt:op=allreduce")
    plan = rt.plan_for("allreduce")
    # clause bits index the FULL parsed spec, so the probe's bitmask stays
    # aligned with the trace-time corrupt rewrites
    assert [(bit, c.verb) for bit, c in plan.clauses] == [(1, "corrupt")]
    assert [(b, c.verb) for b, c in rt.plan_for("barrier").clauses] == [
        (0, "die")
    ]
    assert rt.plan_for("gather") is None                # matches no clause


def test_overrides_shadow_env_and_reset():
    os.environ["MPI4JAX_TPU_WATCHDOG_TIMEOUT"] = "120"
    assert rt.effective_watchdog_timeout() == 120.0
    rt.set_watchdog_timeout(0)                          # programmatic off
    assert rt.effective_watchdog_timeout() is None
    rt.set_watchdog_timeout(7)
    assert rt.effective_watchdog_timeout() == 7.0
    rt.reset_overrides()
    assert rt.effective_watchdog_timeout() == 120.0     # env rules again

    with pytest.raises(ValueError, match="fault spec clause"):
        rt.set_fault_spec("explode:rank=1")             # validated eagerly
    assert rt.effective_fault_clauses() == ()           # bad spec not kept

    # the programmatic path mirrors the env path's validation: a negative
    # timeout would kill a healthy job on the monitor's first scan
    for bad in (-1, float("nan")):
        with pytest.raises(ValueError, match="watchdog timeout"):
            rt.set_watchdog_timeout(bad)


def test_cache_token_reflects_every_knob():
    base = rt.cache_token()
    tokens = {base}
    rt.set_watchdog_timeout(30)
    tokens.add(rt.cache_token())
    rt.set_fault_spec("delay:rank=1")
    tokens.add(rt.cache_token())
    rt.set_check_numerics(True)
    tokens.add(rt.cache_token())
    # each knob must change the compiled-program cache key, or toggling it
    # would silently keep serving the stale program
    assert len(tokens) == 4
    rt.reset_overrides()
    assert rt.cache_token() == base


# ---------------------------------------------------------------------------
# init_distributed bootstrap retry
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_mesh_module(monkeypatch):
    mesh_mod = ISO.parallel.mesh
    monkeypatch.setattr(mesh_mod, "_distributed_initialized", False)
    yield mesh_mod


def test_init_distributed_retries_then_succeeds(fresh_mesh_module, monkeypatch):
    mesh_mod = fresh_mesh_module
    fn = _Flaky(2)
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: fn(),
    )
    mesh_mod.init_distributed(
        coordinator_address="localhost:1", num_processes=2, process_id=0,
        connect_base_delay=0.001, connect_max_delay=0.002,
    )
    assert fn.calls == 3
    assert mesh_mod._distributed_initialized
    mesh_mod.init_distributed()                 # idempotent: no reconnect
    assert fn.calls == 3


def test_init_distributed_deadline_error_names_coordinator(
        fresh_mesh_module, monkeypatch):
    mesh_mod = fresh_mesh_module
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(ConnectionError("refused")),
    )
    with pytest.raises(RuntimeError) as exc_info:
        mesh_mod.init_distributed(
            coordinator_address="badhost:9999", num_processes=2, process_id=0,
            connect_deadline=0.05, connect_base_delay=0.005,
            connect_max_delay=0.01,
        )
    msg = str(exc_info.value)
    assert "badhost:9999" in msg and "attempt" in msg
    assert not mesh_mod._distributed_initialized


def test_init_distributed_already_initialized_not_retried(
        fresh_mesh_module, monkeypatch):
    mesh_mod = fresh_mesh_module
    calls = []

    def fake_init(**kw):
        calls.append(1)
        # JAX's actual double-init message (jax/_src/distributed.py)
        raise RuntimeError("distributed.initialize should only be called once.")

    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", fake_init)
    with pytest.raises(RuntimeError, match="only be called once") as exc_info:
        mesh_mod.init_distributed(
            coordinator_address="localhost:1", num_processes=2, process_id=0,
            connect_deadline=30.0,
        )
    # the giveup escape: re-raised verbatim on the first attempt, not
    # wrapped in the deadline error after 30s of futile retries
    assert len(calls) == 1
    assert "failed after" not in str(exc_info.value)


# ===========================================================================
# JAX-integration half (needs a working mpi4jax_tpu import)
# ===========================================================================


def _subprocess_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


_SUBPROCESS_PRELUDE = textwrap.dedent("""
    import os
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import mpi4jax_tpu as mpx
""")


@needs_mpx
def test_hlo_byte_identical_when_disabled(monkeypatch):
    """Acceptance pin: with every resilience feature off (the default) the
    lowered HLO is byte-identical to an uninstrumented build, and turning a
    knob on changes it (so the pin cannot pass vacuously)."""
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.resilience import runtime as real_rt

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.ones((8, 4))
    default_off = jax.jit(f).lower(x).as_text()
    with monkeypatch.context() as m:
        # the uninstrumented build: the dispatch layer never consults a plan
        m.setattr(real_rt, "plan_for", lambda opname: None)
        uninstrumented = jax.jit(f).lower(x).as_text()
    assert default_off == uninstrumented

    real_rt.set_check_numerics(True)
    try:
        guarded = jax.jit(f).lower(x).as_text()
    finally:
        real_rt.reset_overrides()
    assert guarded != default_off


@needs_mpx
def test_delay_fault_injects_at_dispatch():
    """A delay clause observably slows only the post-``after`` calls of the
    matching op, through the real dispatch path."""
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import resilience

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.arange(8.0)[:, None]
    resilience.set_fault_spec("delay:rank=1:op=allreduce:after=2:secs=0.4")
    resilience.reset_fault_state()
    try:
        np.asarray(f(x))                   # call 1: clean window + compile
        t0 = time.perf_counter()
        clean_run = np.asarray(f(x))       # call 2: clean window, cached
        clean = time.perf_counter() - t0
        t0 = time.perf_counter()
        fired_run = np.asarray(f(x))       # call 3: rank 1 sleeps 0.4s
        fired = time.perf_counter() - t0
    finally:
        resilience.reset_overrides()
        resilience.reset_fault_state()
    # values unharmed: delay is a straggler, not corruption
    assert (clean_run == 28).all() and (fired_run == 28).all()
    assert fired >= clean + 0.25, (clean, fired)


@needs_mpx
def test_watchdog_brackets_collective_cleanly():
    """With a generous timeout the watchdog arms and disarms around a healthy
    collective: values are untouched and nothing stays in flight."""
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import resilience
    from mpi4jax_tpu.resilience import watchdog as real_wd

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    # force the Python-fallback registry even where the native hooks
    # library is built, so this test pins the io_callback bracket (the
    # native bracket's kill path is exercised by the subprocess tests)
    import unittest.mock

    resilience.set_watchdog_timeout(60)
    try:
        with unittest.mock.patch.object(
            mpx.native, "watchdog_supported", lambda: False
        ):
            out = np.asarray(f(jnp.arange(8.0)[:, None]))
    finally:
        resilience.reset_overrides()
    assert (out == 28).all()
    deadline = time.monotonic() + 5.0
    while not real_wd.registry_empty() and time.monotonic() < deadline:
        time.sleep(0.05)  # disarm callbacks may trail block_until_ready
    assert real_wd.registry_empty(), real_wd.inflight_snapshot()


@needs_mpx
def test_die_fault_kills_process_from_env_spec():
    """End-to-end ``die``: the spec comes in through the environment, fires
    at the dispatch point, and kills the process with exit code 13."""
    script = _SUBPROCESS_PRELUDE + textwrap.dedent("""
        import numpy as np

        @mpx.spmd
        def f(x):
            res, _ = mpx.allreduce(x, op=mpx.SUM)
            return res

        out = np.asarray(f(jnp.arange(8.0)[:, None]))
        assert (out == 28).all()           # call 1 is inside the after window
        f(jnp.arange(8.0)[:, None]).block_until_ready()
        print("SHOULD NOT REACH", flush=True)
    """)
    env = _subprocess_env()
    env["MPI4JAX_TPU_FAULT_SPEC"] = "die:rank=5:op=allreduce:after=1"
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert proc.returncode == 13, proc.stderr[-4000:]
    assert "r5 | FAULT | die injected in MPI_Allreduce" in proc.stderr
    assert "SHOULD NOT REACH" not in proc.stdout


@needs_mpx
def test_corrupt_nan_aborts_under_check_numerics():
    """corrupt:nan + CHECK_NUMERICS: the injected NaN is caught at the
    collective boundary and the abort names the op."""
    script = _SUBPROCESS_PRELUDE + textwrap.dedent("""
        from mpi4jax_tpu import native
        if not native.available():
            native.build(verbose=False)

        @mpx.spmd
        def f(x):
            res, _ = mpx.allreduce(x, op=mpx.SUM)
            return res

        f(jnp.arange(8.0)[:, None]).block_until_ready()
        print("SHOULD NOT REACH", flush=True)
    """)
    env = _subprocess_env()
    env["MPI4JAX_TPU_FAULT_SPEC"] = "corrupt:nan:rank=2:op=allreduce"
    env["MPI4JAX_TPU_CHECK_NUMERICS"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert proc.returncode != 0, proc.stdout
    assert "FAULT | corrupt:nan injected in MPI_Allreduce" in proc.stderr
    assert re.search(
        r"FATAL: MPI_Allreduce: non-finite (input|output) detected "
        r"\(MPI4JAX_TPU_CHECK_NUMERICS", proc.stderr,
    ), proc.stderr[-4000:]
    assert "SHOULD NOT REACH" not in proc.stdout


@needs_mpx
def test_check_numerics_passes_finite_values():
    """The guard is not trigger-happy: finite traffic flows untouched."""
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import resilience

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    resilience.set_check_numerics(True)
    try:
        out = np.asarray(f(jnp.arange(8.0)[:, None]))
    finally:
        resilience.reset_overrides()
    assert (out == 28).all()


# the flagship fail-fast drill (ISSUE acceptance): a 2-process job where an
# injected `die` kills rank 1; rank 0 hangs in the next collective and its
# watchdog must abort it — naming the in-flight op — within 2x the timeout.
WATCHDOG_TIMEOUT_S = 5.0

_HANG_WORKER = textwrap.dedent("""
    import os, sys
    proc_id = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, sys.argv[3])
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import resilience

    mpx.init_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=2, process_id=proc_id,
    )
    assert jax.device_count() == 2

    # rank 1 dies in its second allreduce; every rank's watchdog is armed
    resilience.set_watchdog_timeout(float(sys.argv[4]))
    resilience.set_fault_spec("die:rank=1:op=allreduce:after=1")

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.arange(2.0)
    out = f(x)                      # step 1: clean for both ranks
    for s in out.addressable_shards:
        assert np.asarray(s.data)[0] == 1.0
    print(f"STEP1_OK {proc_id}", flush=True)
    try:
        f(x).block_until_ready()    # step 2: rank 1 dies; rank 0 hangs
        print(f"SHOULD NOT REACH {proc_id}", flush=True)
    except Exception as e:
        # the peer's death surfaced as a collective error instead of a
        # hang; the watchdog entry armed for this collective was never
        # disarmed, so the monitor still owes the diagnostic + kill --
        # wait for it rather than exiting on our own terms
        import time
        print(f"COLLECTIVE_ERROR {proc_id}: {e}", flush=True)
        time.sleep(120)
""")


@pytest.mark.slow
@needs_mpx
def test_watchdog_aborts_hung_rank_after_injected_death():
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _HANG_WORKER, str(i), port, str(REPO),
             str(WATCHDOG_TIMEOUT_S)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    # generous wall budget for startup + step 1; the 2x-timeout bound is
    # asserted from the watchdog's own elapsed measurement below, which
    # starts when the doomed collective arms
    try:
        out1, err1 = procs[1].communicate(timeout=300)
        out0, err0 = procs[0].communicate(timeout=300)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert procs[1].returncode == 13, (out1, err1[-4000:])
    assert "die injected in MPI_Allreduce" in err1
    assert "STEP1_OK 1" in out1

    # rank 0: loud watchdog death, not a hang — diagnostics name the op
    assert procs[0].returncode != 0, (out0, err0[-4000:])
    assert "SHOULD NOT REACH 0" not in out0
    assert "STEP1_OK 0" in out0
    m = re.search(
        r"WATCHDOG \| in-flight: MPI_Allreduce \(call [0-9a-f]{8}, "
        r"axes=.*elapsed (\d+\.\d+)s\)", err0)
    assert m, err0[-4000:]
    elapsed = float(m.group(1))
    assert elapsed <= 2 * WATCHDOG_TIMEOUT_S, elapsed
    assert re.search(
        r"FATAL: collective watchdog: MPI_Allreduce exceeded "
        + re.escape(f"{WATCHDOG_TIMEOUT_S:g}") + "s", err0), err0[-4000:]
