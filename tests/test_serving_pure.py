"""Serving runtime: the pure-Python half (docs/serving.md).

Bucket table and pad-up rule, the declared-bucket registry, the KV slot
allocator, continuous/static scheduler admission + eviction ordering,
Poisson trace determinism (seeded generator), SLO accounting, the
serving config + per-(bucket, phase) program shapes, the warm-manifest
emission (parsed back through the aot CLI's own validator), the MPX136
checker, the megastep boundary-hook registry, the elastic
BoundaryControl drain path on a scripted store, the cost-model replay
(continuous must beat static on a saturated heavy-tail trace), and the
padded-bucket ``overlap_chunks`` regression — all loaded under a
private package name (the isolated-loader idiom of
tests/test_autotune_pure.py) so everything runs even where the
installed JAX is below the package's floor.

The traced half — pinned-per-bucket bit-identity, megastep-boundary
admission, the live drain drill — is tests/test_serving.py (needs
jax >= the package floor).
"""

import importlib
import sys
import types
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_serving_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    # no "ops" stub: nothing in the pure serving half imports the op
    # stack at module level, and revoke_epoch's guarded cache-drop must
    # see the package as absent (not as an empty stub)
    for sub in ("utils", "analysis", "parallel", "resilience",
                "serving", "aot", "autotune"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "analysis.report", "analysis.graph",
                "analysis.checkers", "analysis.costmodel",
                "parallel.megastep", "resilience.faultinject",
                "resilience.retry", "resilience.watchdog",
                "resilience.elastic", "autotune.schema",
                "serving.buckets", "serving.kvcache", "serving.metrics",
                "serving.scheduler", "serving.model", "serving.engine",
                "serving.sim", "aot.warm"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = ISO.utils.config
buckets = sys.modules[f"{_ISO_NAME}.serving.buckets"]
kvcache = sys.modules[f"{_ISO_NAME}.serving.kvcache"]
metrics = sys.modules[f"{_ISO_NAME}.serving.metrics"]
scheduler = sys.modules[f"{_ISO_NAME}.serving.scheduler"]
engine = sys.modules[f"{_ISO_NAME}.serving.engine"]
sim = sys.modules[f"{_ISO_NAME}.serving.sim"]
megastep = sys.modules[f"{_ISO_NAME}.parallel.megastep"]
elastic = sys.modules[f"{_ISO_NAME}.resilience.elastic"]
warm = sys.modules[f"{_ISO_NAME}.aot.warm"]
graphmod = sys.modules[f"{_ISO_NAME}.analysis.graph"]
checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
report = sys.modules[f"{_ISO_NAME}.analysis.report"]

E = graphmod.CollectiveEvent
G = graphmod.CollectiveGraph

SERVING_FLAGS = ("MPI4JAX_TPU_SERVING_MAX_BATCH",
                 "MPI4JAX_TPU_SERVING_BUCKETS",
                 "MPI4JAX_TPU_SERVING_KV_SLOTS",
                 "MPI4JAX_TPU_SERVING_UNROLL",
                 "MPI4JAX_TPU_SERVING_SLO_P99_MS")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for name in SERVING_FLAGS + ("MPI4JAX_TPU_OVERLAP_CHUNKS",
                                 "MPI4JAX_TPU_TUNING"):
        monkeypatch.delenv(name, raising=False)
    buckets.clear_declared_buckets()
    config.load_tuning(None)
    yield
    buckets.clear_declared_buckets()
    config.load_tuning(None)


# ---------------------------------------------------------------------------
# bucket table
# ---------------------------------------------------------------------------


def test_powers_of_two():
    assert buckets.powers_of_two(8) == (1, 2, 4, 8)
    assert buckets.powers_of_two(1) == (1,)
    assert buckets.powers_of_two(6) == (1, 2, 4, 6)
    with pytest.raises(ValueError):
        buckets.powers_of_two(0)


def test_bucket_for_and_pad():
    t = buckets.BucketTable((1, 2, 4, 8))
    assert [t.bucket_for(n) for n in (1, 2, 3, 4, 5, 8)] == \
        [1, 2, 4, 4, 8, 8]
    assert t.pad(5) == 3 and t.pad(8) == 0
    assert t.max_batch == 8
    assert 4 in t and 5 not in t
    with pytest.raises(ValueError):
        t.bucket_for(0)
    with pytest.raises(ValueError):
        t.bucket_for(9)


@pytest.mark.parametrize("bad", [(), (0, 2), (2, 1), (1, 1, 2), (1, -4)])
def test_bucket_table_rejects(bad):
    with pytest.raises(ValueError):
        buckets.BucketTable(bad)


def test_bucket_spec_parsing():
    assert buckets.BucketTable.from_spec("", 8).buckets == (1, 2, 4, 8)
    assert buckets.BucketTable.from_spec("1,3,6").buckets == (1, 3, 6)
    with pytest.raises(ValueError):
        buckets.BucketTable.from_spec("1,two")
    with pytest.raises(ValueError):
        buckets.BucketTable.from_spec("")


def test_declared_registry():
    assert buckets.declared_buckets() is None
    t = buckets.declare_buckets((1, 2, 4))
    assert buckets.declared_buckets() is t
    t2 = buckets.declare_buckets(buckets.BucketTable((1, 8)))
    assert buckets.declared_buckets() is t2
    buckets.clear_declared_buckets()
    assert buckets.declared_buckets() is None


def test_bucket_payload_bytes():
    assert buckets.bucket_payload_bytes(8, 96 * 4) == 8 * 96 * 4
    with pytest.raises(ValueError):
        buckets.bucket_payload_bytes(0, 4)


# ---------------------------------------------------------------------------
# slot allocator
# ---------------------------------------------------------------------------


def test_slot_allocator_deterministic_order():
    a = kvcache.SlotAllocator(4)
    assert [a.alloc() for _ in range(4)] == [0, 1, 2, 3]
    a.free_slot(2)
    a.free_slot(0)
    # freed slots re-issue lowest-first regardless of free order
    assert a.alloc() == 0 and a.alloc() == 2
    assert a.free() == 0


def test_slot_allocator_errors():
    a = kvcache.SlotAllocator(1)
    with pytest.raises(ValueError):
        a.free_slot(0)          # not allocated
    s = a.alloc()
    with pytest.raises(RuntimeError):
        a.alloc()               # exhausted
    a.free_slot(s)
    assert a.scratch == 1       # outside the pool
    with pytest.raises(ValueError):
        kvcache.SlotAllocator(0)


# ---------------------------------------------------------------------------
# trace generator
# ---------------------------------------------------------------------------


def test_poisson_trace_deterministic():
    a = scheduler.poisson_trace(32, 100.0, seed=3, long_frac=0.25,
                                long_new=(32, 64))
    b = scheduler.poisson_trace(32, 100.0, seed=3, long_frac=0.25,
                                long_new=(32, 64))
    assert [(r.arrival_s, r.prompt, r.max_new_tokens) for r in a] == \
        [(r.arrival_s, r.prompt, r.max_new_tokens) for r in b]
    c = scheduler.poisson_trace(32, 100.0, seed=4)
    assert [r.arrival_s for r in a] != [r.arrival_s for r in c]


def test_poisson_trace_shape():
    trace = scheduler.poisson_trace(64, 100.0, seed=0, prompt_len=(2, 5),
                                    max_new=(4, 8), long_frac=0.5,
                                    long_new=(20, 30))
    arrivals = [r.arrival_s for r in trace]
    assert arrivals == sorted(arrivals) and arrivals[0] > 0
    assert all(2 <= r.prompt_len <= 5 for r in trace)
    assert all(4 <= r.max_new_tokens <= 8 or 20 <= r.max_new_tokens <= 30
               for r in trace)
    assert any(r.max_new_tokens >= 20 for r in trace)
    with pytest.raises(ValueError):
        scheduler.poisson_trace(0, 1.0)
    with pytest.raises(ValueError):
        scheduler.poisson_trace(1, 0.0)
    with pytest.raises(ValueError):
        scheduler.poisson_trace(1, 1.0, long_frac=1.5)


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


def _mktrace(n, arrival=0.0, max_new=4):
    return [scheduler.Request(rid=i, arrival_s=arrival, prompt=(1, 2),
                              max_new_tokens=max_new) for i in range(n)]


def _sched(cls=scheduler.ContinuousScheduler, max_batch=4, slots=8):
    table = buckets.BucketTable.from_spec("", max_batch)
    return cls(table, kvcache.SlotAllocator(slots))


def test_admission_fifo_and_bounds():
    s = _sched(max_batch=4, slots=8)
    trace = _mktrace(6)
    assert s.offer(trace, now=0.0) == 6
    new = s.admit(0.0)
    # FIFO, bounded by max_batch
    assert [q.rid for q in new] == [0, 1, 2, 3]
    assert len(s.waiting) == 2
    assert s.decode_bucket() == 4
    # a finished sequence frees its lane and slot; next boundary admits
    s.running[0].record([9] * 4, 1.0)
    done = s.finish_ready(1.0)
    assert [q.rid for q in done] == [0]
    assert [q.rid for q in s.admit(1.0)] == [4]


def test_admission_slot_bound():
    s = _sched(max_batch=8, slots=2)
    s.offer(_mktrace(5), 0.0)
    assert len(s.admit(0.0)) == 2  # KV budget binds before max_batch
    assert s.alloc.free() == 0


def test_static_scheduler_gates_on_drain():
    s = _sched(cls=scheduler.StaticScheduler, max_batch=4, slots=8)
    s.offer(_mktrace(8), 0.0)
    assert len(s.admit(0.0)) == 4
    s.running[0].record([9] * 4, 0.5)
    s.finish_ready(0.5)
    # batch not fully drained: nothing admitted
    assert s.admit(0.5) == []
    for q in list(s.running):
        q.record([9] * 4, 1.0)
    s.finish_ready(1.0)
    # drained: the next WHOLE batch comes in at once
    assert len(s.admit(1.0)) == 4


def test_sequence_record_caps_overshoot():
    q = scheduler.Sequence(request=_mktrace(1, max_new=3)[0], slot=0,
                           admitted_s=0.0)
    q.record([5, 6, 7, 8], 1.0)   # a megastep overshoots by one
    assert q.generated == [5, 6, 7] and q.done
    assert q.finish_s == 1.0 and q.first_token_s == 1.0
    assert q.tokens == (1, 2, 5, 6, 7)


def test_requeue_and_readmit():
    s = _sched(max_batch=4, slots=4)
    s.offer(_mktrace(3), 0.0)
    s.admit(0.0)
    moved = s.requeue_running()
    assert len(moved) == 3 and not s.running and s.alloc.free() == 4
    s.readmit(moved)
    assert [q.rid for q in s.running] == [0, 1, 2]
    assert all(q.preempt_readmissions == 1 for q in s.running)


def test_idle():
    s = _sched()
    trace = _mktrace(1, arrival=5.0)
    assert not s.idle(trace)          # not yet offered
    s.offer(trace, 10.0)
    s.admit(10.0)
    assert not s.idle(trace)
    s.running[0].record([9] * 4, 11.0)
    s.finish_ready(11.0)
    assert s.idle(trace)
    assert s.next_arrival_s(trace) is None


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_percentile():
    assert metrics.percentile([], 0.5) is None
    assert metrics.percentile([3.0], 0.99) == 3.0
    vals = [float(i) for i in range(1, 101)]
    assert metrics.percentile(vals, 0.5) == 51.0
    assert metrics.percentile(vals, 0.99) == 99.0
    with pytest.raises(ValueError):
        metrics.percentile([1.0], 1.5)


def test_summarize_and_bench_payload():
    trace = _mktrace(2, arrival=1.0, max_new=2)
    done = []
    for i, r in enumerate(trace):
        q = scheduler.Sequence(request=r, slot=i, admitted_s=1.0)
        q.record([5, 5], 1.0 + 0.1 * (i + 1))
        done.append(q)
    cont = metrics.summarize(done, wall_s=2.0, chips=4, slo_p99_ms=500.0)
    assert cont["completed"] == 2 and cont["failed"] == 0
    assert cont["tokens"] == 4
    assert cont["tokens_per_s_per_chip"] == round(4 / 2.0 / 4, 3)
    assert cont["p99_ms"] == pytest.approx(200.0)
    assert cont["slo_met"] is True
    stat = dict(cont, tokens_per_s_per_chip=0.25, scheduler="static")
    payload = metrics.bench_payload(
        workload={"model": "m"}, trace_meta={"requests": 2}, chips=4,
        continuous=cont, static=stat, environment="test")
    assert payload["schema"] == metrics.BENCH_SCHEMA
    assert payload["speedup_tokens_per_s"] == \
        round(cont["tokens_per_s_per_chip"] / 0.25, 3)
    assert payload["slo_p99_ms"] == 500.0


def test_summarize_slo_violation():
    r = _mktrace(1, arrival=0.0, max_new=1)[0]
    q = scheduler.Sequence(request=r, slot=0, admitted_s=0.0)
    q.record([5], 2.0)
    out = metrics.summarize([q], wall_s=2.0, chips=1, slo_p99_ms=100.0)
    assert out["p99_ms"] == pytest.approx(2000.0)
    assert out["slo_met"] is False


# ---------------------------------------------------------------------------
# serving config + program shapes + warm manifest
# ---------------------------------------------------------------------------


def test_config_from_env(monkeypatch):
    cfg = engine.ServingConfig.from_env()
    assert cfg.max_batch == config.DEFAULT_SERVING_MAX_BATCH
    assert cfg.unroll == config.DEFAULT_SERVING_UNROLL
    assert cfg.slo_p99_ms == config.DEFAULT_SERVING_SLO_P99_MS
    assert cfg.table().buckets == (1, 2, 4, 8)
    assert cfg.slots() == 2 * cfg.max_batch
    monkeypatch.setenv("MPI4JAX_TPU_SERVING_MAX_BATCH", "4")
    monkeypatch.setenv("MPI4JAX_TPU_SERVING_BUCKETS", "2,4")
    monkeypatch.setenv("MPI4JAX_TPU_SERVING_KV_SLOTS", "5")
    monkeypatch.setenv("MPI4JAX_TPU_SERVING_UNROLL", "2")
    monkeypatch.setenv("MPI4JAX_TPU_SERVING_SLO_P99_MS", "250")
    cfg = engine.ServingConfig.from_env()
    assert cfg.max_batch == 4 and cfg.buckets == (2, 4)
    assert cfg.slots() == 5 and cfg.unroll == 2
    assert cfg.slo_p99_ms == 250.0
    # explicit overrides win over env
    cfg = engine.ServingConfig.from_env(unroll=8)
    assert cfg.unroll == 8


def test_config_validation():
    cfg = engine.ServingConfig()           # heads=24, ffn=384
    cfg.validate_world(8)
    cfg.validate_world(3)
    with pytest.raises(ValueError):
        cfg.validate_world(5)              # 24 % 5 != 0
    with pytest.raises(ValueError):
        engine.ServingConfig(max_prompt=0).validate_world(1)
    with pytest.raises(ValueError):
        # bucket table must top out at max_batch
        engine.ServingConfig(buckets=(1, 2), max_batch=8).table()
    cfg.budget_check(8, 16)
    with pytest.raises(ValueError):
        cfg.budget_check(cfg.max_prompt + 1, 1)
    with pytest.raises(ValueError):
        cfg.budget_check(4, cfg.max_len)   # cannot fit the KV row


def test_program_args_shapes():
    cfg = engine.ServingConfig()
    k = 8
    hl = cfg.heads // k
    pre = cfg.program_args("prefill", 4, k)
    dec = cfg.program_args("decode", 4, k)
    rep = cfg.program_args("replay", 4, k)
    # 5 params + kk + vv + tok + 3 lane arrays
    assert len(pre) == len(dec) == len(rep) == 11
    kvs = (k, cfg.slots() + 1, cfg.max_len, hl, cfg.head_dim)
    assert pre[5] == (kvs, "float32") and pre[6] == (kvs, "float32")
    assert pre[8] == ((k, 4, cfg.max_prompt), "int32")
    assert rep[8] == ((k, 4, cfg.max_len), "int32")
    assert dec[8] == ((k, 4), "int32")
    with pytest.raises(ValueError):
        cfg.program_args("sample", 4, k)


def test_collective_payload_is_padded():
    cfg = engine.ServingConfig()
    # the decode collective payload is derived from the BUCKET, so two
    # live batch sizes in one bucket consult every payload-keyed knob
    # with the same bytes
    assert cfg.collective_payload_bytes(4) == 4 * cfg.dim * 4
    t = cfg.table()
    assert t.bucket_for(3) == t.bucket_for(4) == 4


def test_warm_manifest_round_trip():
    cfg = engine.ServingConfig()
    man = engine.warm_manifest(cfg, 8)
    specs = warm.parse_manifest(man)     # the aot CLI's own validator
    assert len(specs) == 3 * len(cfg.table().buckets)
    labels = {s.label for s in specs}
    for b in cfg.table().buckets:
        for phase in engine.ALL_PHASES:
            assert f"serving.{phase}.b{b}" in labels
    for s in specs:
        assert s.fn.startswith("mpi4jax_tpu.serving.model:")
        assert s.unroll == (cfg.unroll if "decode" in s.label else 1)
        # manifest shapes ARE the engine's pin shapes
        phase = s.label.split(".")[1]
        b = int(s.label.rsplit(".b", 1)[1])
        want = cfg.program_args(phase, b, 8)
        got = [(tuple(a["shape"]), a["dtype"]) for a in s.args]
        assert got == want
    with pytest.raises(ValueError):
        engine.warm_manifest(cfg, 5)     # unshardable world


# ---------------------------------------------------------------------------
# MPX136
# ---------------------------------------------------------------------------


def _ev(i, shape, op="allreduce", eager=False):
    return E(index=i, op=op, shape=shape,
             payload_bytes=4 * int.__mul__(*shape[:2]) if len(shape) > 1
             else 0, eager=eager)


def test_mpx136_positive():
    g = G(events=[_ev(0, (5, 96)), _ev(1, (4, 96)), _ev(2, (5, 96)),
                  _ev(3, (7, 96))],
          meta={"serving_buckets": (1, 2, 4, 8)})
    fs = checkers.check_unbucketed_batch(g)
    assert [f.code for f in fs] == ["MPX136", "MPX136"]
    assert all(f.severity == "advisory" for f in fs)
    assert "5" in fs[0].message and "7" in fs[1].message
    assert "bucket" in fs[0].suggestion


def test_mpx136_negative():
    events = [_ev(0, (4, 96)), _ev(1, (8, 96))]
    # in-bucket shapes: clean
    assert not checkers.check_unbucketed_batch(
        G(events=events, meta={"serving_buckets": (1, 2, 4, 8)}))
    # no declared table: inert even with odd shapes
    assert not checkers.check_unbucketed_batch(
        G(events=[_ev(0, (5, 96))], meta={}))
    # eager events and shapeless events never count
    g = G(events=[_ev(0, (5, 96), eager=True),
                  E(index=1, op="barrier", shape=())],
          meta={"serving_buckets": (1, 2, 4, 8)})
    assert not checkers.check_unbucketed_batch(g)


def test_mpx136_catalog():
    info = report.CODES["MPX136"]
    assert info.severity == report.ADVISORY
    assert "bucket" in info.doc
    # owned by exactly the checker above
    assert "MPX136" in checkers.registered_codes()


def test_mpx136_through_run_checkers():
    g = G(events=[_ev(0, (5, 96))],
          meta={"serving_buckets": (1, 2, 4, 8), "pinned": True})
    codes = {f.code for f in checkers.run_checkers(g)}
    assert "MPX136" in codes


# ---------------------------------------------------------------------------
# megastep boundary hooks
# ---------------------------------------------------------------------------


def test_boundary_hooks_order_and_unregister():
    calls = []
    u1 = megastep.register_boundary_hook("a", lambda s, **kw: calls.append(
        ("a", s, kw.get("engine"))))
    u2 = megastep.register_boundary_hook("b", lambda s, **kw: calls.append(
        ("b", s, None)))
    try:
        out = megastep.run_boundary_hooks(7, engine="E")
        assert [n for n, _ in out] == ["a", "b"]
        assert calls == [("a", 7, "E"), ("b", 7, None)]
    finally:
        u1()
        u2()
    assert megastep.run_boundary_hooks(8) == []
    u1()  # double-unregister is a no-op
    with pytest.raises(TypeError):
        megastep.register_boundary_hook("bad", None)


def test_boundary_hook_exceptions_propagate():
    def boom(step, **kw):
        raise RuntimeError("stop the loop")

    u = megastep.register_boundary_hook("boom", boom)
    try:
        with pytest.raises(RuntimeError):
            megastep.run_boundary_hooks(1)
    finally:
        u()


# ---------------------------------------------------------------------------
# BoundaryControl: the scripted single-controller drain
# ---------------------------------------------------------------------------


class _FakeMesh:
    shape = {"x": 4}


class _FakeComm:
    def __init__(self, uid=9001, size=4):
        self.uid = uid
        self._size = size
        self.mesh = _FakeMesh()
        self.epoch = 0

    def world_size(self):
        return self._size


class _FakeStore:
    """The minimal surface _boundary_actions touches on the
    single-controller drain path."""

    def __init__(self):
        self.comm = _FakeComm()
        self.bootstrap = {}
        self.commits = []
        self.shrinks = []
        self.drained = False
        self.committed_step = 0

    def multiprocess(self):
        return False

    def commit(self, step, state):
        self.commits.append(step)

    def apply_shrink(self, removed, unit):
        self.shrinks.append((tuple(sorted(removed)), unit))
        self.comm = _FakeComm(uid=self.comm.uid + 1,
                              size=self.comm.world_size() - len(removed))


@pytest.fixture
def _fresh_epoch():
    elastic._reset_epoch_for_tests()
    yield
    elastic._reset_epoch_for_tests()


def test_boundary_control_single_controller_drain(_fresh_epoch):
    store = _FakeStore()
    with elastic.BoundaryControl(store) as bc:
        assert bc.poll(0, {"x": 1}) is None
        elastic.request_drain(rank=3)
        outcome = bc.poll(1, {"x": 1}, committed=False)
    assert outcome is not None and outcome[0] == "continue"
    # the drain force-committed (committed=False) and shrank rank 3 out
    assert store.commits == [1]
    assert store.shrinks == [((3,), "rank")]
    assert elastic.current_epoch() == 1
    # the old comm is sealed past its leave boundary
    assert elastic.comm_drained(store.comm.uid - 1)


def test_boundary_control_noop_poll(_fresh_epoch):
    store = _FakeStore()
    with elastic.BoundaryControl(store) as bc:
        for step in range(3):
            assert bc.poll(step, None) is None
    assert store.shrinks == [] and elastic.current_epoch() == 0


# ---------------------------------------------------------------------------
# cost-model replay: continuous beats static on a heavy-tail trace
# ---------------------------------------------------------------------------


def _bench_cfg():
    return engine.ServingConfig(heads=24, head_dim=64, ffn=6144,
                                max_len=160, max_prompt=16, max_batch=8,
                                unroll=8, slo_p99_ms=1000.0)


def _bench_trace():
    return scheduler.poisson_trace(
        192, 8000.0, seed=7, prompt_len=(4, 16), max_new=(8, 24),
        long_frac=0.25, long_new=(96, 128))


def test_replay_deterministic():
    cfg = _bench_cfg()
    a = sim.replay(cfg, _bench_trace(), k=8)
    b = sim.replay(cfg, _bench_trace(), k=8)
    assert a == b


def test_replay_continuous_beats_static():
    cfg = _bench_cfg()
    trace = _bench_trace()
    payload, cont, stat = sim.replay_bench(
        cfg, trace, k=8, trace_meta={"requests": len(trace)})
    assert cont["failed"] == 0 and stat["failed"] == 0
    assert cont["completed"] == stat["completed"] == len(trace)
    assert payload["speedup_tokens_per_s"] >= 1.5, payload
    assert cont["slo_met"], cont
    # continuous batching also improves the tail, not just throughput
    assert cont["p99_ms"] < stat["p99_ms"]
    assert payload["schema"] == metrics.BENCH_SCHEMA
    assert "static" in payload and "continuous" in payload


def test_replay_step_costs_shape():
    cfg = _bench_cfg()
    costs = sim.step_costs_us(cfg, 8)
    assert costs["dispatch"] > 0
    for b in cfg.table().buckets:
        assert costs[f"decode.b{b}"] > 0
        assert costs[f"prefill.b{b}"] > 0
    # bigger buckets cost at least as much per step
    assert costs["decode.b8"] >= costs["decode.b1"]


def test_committed_bench_artifact():
    """The committed BENCH_serving.json must carry both scheduler
    numbers at one SLO, a >= 1.5x continuous-over-static speedup, and
    zero failed requests (the acceptance bar of docs/serving.md)."""
    import json

    path = REPO / "BENCH_serving.json"
    assert path.exists(), "BENCH_serving.json missing"
    payload = json.loads(path.read_text())
    assert payload["schema"] == metrics.BENCH_SCHEMA
    assert payload["slo_p99_ms"] > 0
    cont, stat = payload["continuous"], payload["static"]
    assert cont["slo_p99_ms"] == stat["slo_p99_ms"] == \
        payload["slo_p99_ms"]
    assert cont["failed"] == 0 and stat["failed"] == 0
    assert cont["slo_met"] is True
    assert cont["tokens_per_s_per_chip"] > 0
    assert stat["tokens_per_s_per_chip"] > 0
    assert payload["speedup_tokens_per_s"] >= 1.5
    assert "environment" in payload


# ---------------------------------------------------------------------------
# the padded-bucket overlap_chunks regression (docs/serving.md)
# ---------------------------------------------------------------------------


def _tuning_with_chunk_boundary(boundary_bytes):
    return {
        "schema": "mpx-tuning/1",
        "tuned": {"overlap_chunks": [
            {"max_bytes": boundary_bytes, "chunks": 2},
            {"max_bytes": None, "chunks": 8},
        ]},
    }


def test_overlap_chunks_consulted_at_padded_payload():
    """Two live batches in ONE serving bucket must derive ONE chunk
    count: the payload every payload-bucketed knob sees at trace time
    is the PADDED bucket payload (bucket_payload_bytes), never the live
    payload.  The tuning boundary here is placed BETWEEN the two live
    payloads, so consulting with live bytes would split the bucket
    across two chunk counts — two traces, two cache keys."""
    cfg = engine.ServingConfig()
    per_item = cfg.dim * 4
    live_a, live_b = 3, 4                   # same bucket (4)
    bucket = cfg.table().bucket_for(live_a)
    assert bucket == cfg.table().bucket_for(live_b)
    boundary = (live_a * per_item + live_b * per_item) // 2
    config.load_tuning(_tuning_with_chunk_boundary(boundary))
    try:
        # the hazard: live payloads straddle the tuning boundary
        assert config.overlap_chunks(live_a * per_item) != \
            config.overlap_chunks(live_b * per_item)
        # the rule: both consult at the padded bucket payload
        padded = buckets.bucket_payload_bytes(bucket, per_item)
        assert cfg.collective_payload_bytes(bucket) == padded
        assert config.overlap_chunks(padded) == \
            config.overlap_chunks(padded)
        assert config.overlap_chunks(padded) == 8
    finally:
        config.load_tuning(None)


def test_overlap_chunks_env_still_wins(monkeypatch):
    cfg = engine.ServingConfig()
    config.load_tuning(_tuning_with_chunk_boundary(1024))
    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", "3")
    assert config.overlap_chunks(
        cfg.collective_payload_bytes(8)) == 3
