"""Data-parallel training example tests (SURVEY.md §2.6(2)).

The acceptance property is *data-parallel equivalence*: DP-SGD over N
ranks with gradient averaging must produce exactly the same weight
trajectory as single-device SGD on the concatenated batch."""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

from data_parallel_training import (  # noqa: E402
    init_mlp,
    local_loss,
    make_train_step,
    replicate,
)

SIZE = 8


def _data(seed=0):
    key = jax.random.PRNGKey(seed)
    key, kx, kn = jax.random.split(key, 3)
    x = jax.random.normal(kx, (SIZE, 32, 16))
    w_true = jax.random.normal(kn, (16, 1))
    return key, x, jnp.tanh(x @ w_true)


def test_loss_decreases_and_weights_replicated():
    key, x, y = _data()
    comm = mpx.get_default_comm()
    params = replicate(init_mlp(key, (16, 32, 1)), SIZE)
    train_step = make_train_step(comm, lr=1e-2)

    first = None
    for _ in range(30):
        params, loss = train_step(params, x, y)
        if first is None:
            first = float(np.asarray(loss)[0])
    last = float(np.asarray(loss)[0])
    assert last < first

    for leaf in jax.tree.leaves(params):
        leaf = np.asarray(leaf)
        np.testing.assert_allclose(
            leaf, np.broadcast_to(leaf[0], leaf.shape), rtol=1e-6
        )


def test_matches_single_device_sgd():
    key, x, y = _data(1)
    comm = mpx.get_default_comm()
    params0 = init_mlp(key, (16, 32, 1))

    # distributed: 5 DP steps over 8 rank-shards
    params = replicate(params0, SIZE)
    train_step = make_train_step(comm, lr=1e-2)
    for _ in range(5):
        params, _ = train_step(params, x, y)
    dp_params = jax.tree.map(lambda v: np.asarray(v)[0], params)

    # single device: same 5 steps on the concatenated batch.  Average of
    # per-shard mean losses == full-batch mean loss (equal shard sizes),
    # so the updates must coincide.
    x_full = x.reshape(-1, 16)
    y_full = y.reshape(-1, 1)
    sd_params = params0
    grad_fn = jax.jit(jax.grad(local_loss))
    for _ in range(5):
        g = grad_fn(sd_params, x_full, y_full)
        sd_params = jax.tree.map(lambda p, gg: p - 1e-2 * gg, sd_params, g)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, np.asarray(b), rtol=5e-5, atol=1e-6
        ),
        dp_params, sd_params,
    )
