"""Example-as-test: the shallow-water demo (SURVEY.md §4 "Example-as-test",
ref tests/test_examples.py:20-24 runs the demo and asserts snapshot count).

Beyond the reference's smoke test, the SPMD design enables a much stronger
property the reference cannot test in one process: *decomposition
invariance* — the same model run on a (2, 4) mesh and on a single device
must produce the same fields.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

from shallow_water import (  # noqa: E402
    Config,
    initial_state,
    reassemble,
    solve,
    solve_fused,
)


def test_shallow_water_runs_and_snapshots():
    # ref tests/test_examples.py asserts >100 snapshots over 1 model day;
    # scaled down here (30 steps, multistep 10 -> 5 snapshots) to keep CI
    # fast while exercising the identical code path
    cfg = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    snaps, wall, n_steps = solve(cfg, 30 * cfg.dt, num_multisteps=10)
    assert n_steps >= 30
    assert len(snaps) >= 4
    final = reassemble(snaps[-2], cfg)
    # water height stays near the resting depth (stable integration)
    assert np.all(np.isfinite(final))
    assert 90 < final.mean() < 110


def test_shallow_water_decomposition_invariance():
    steps = 20
    cfg8 = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    s8, _, _ = solve(cfg8, steps * cfg8.dt, num_multisteps=5)
    cfg1 = Config(nproc_y=1, nproc_x=1, nx=48, ny=24)
    s1, _, _ = solve(cfg1, steps * cfg1.dt, num_multisteps=5,
                     devices=jax.devices()[:1])
    g8 = reassemble(s8[-2], cfg8)
    g1 = reassemble(s1[-2], cfg1)
    np.testing.assert_allclose(g8, g1, rtol=1e-5, atol=1e-4)


def test_shallow_water_gathered_solution_matches_stacked():
    cfg = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    snaps, _, _ = solve(cfg, 10 * cfg.dt, num_multisteps=5)
    # the last snapshot is the eager-gather copy of the final stacked state:
    # identical values in identical rank order (catches any gather
    # rank-ordering regression on the multi-axis comm)
    assert snaps[-1].shape == snaps[0].shape
    np.testing.assert_array_equal(snaps[-1], snaps[-2])


def test_solve_fused_matches_host_loop_step_count():
    # the fused (single-dispatch) benchmark path must run exactly the same
    # number of model steps as the host-loop path
    cfg = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    t1 = 23 * cfg.dt
    _, _, n_host = solve(cfg, t1, num_multisteps=5, collect=False)
    wall, n_fused = solve_fused(cfg, t1, num_multisteps=5)
    assert n_fused == n_host
    assert wall > 0


def test_initial_state_decomposition_independent():
    cfg8 = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    cfg1 = Config(nproc_y=1, nproc_x=1, nx=48, ny=24)
    g8 = reassemble(np.asarray(initial_state(cfg8).h), cfg8)
    g1 = reassemble(np.asarray(initial_state(cfg1).h), cfg1)
    np.testing.assert_array_equal(g8, g1)


@pytest.mark.parametrize("periodic", [True, False])
def test_shallow_water_boundary_modes(periodic):
    from dataclasses import replace

    cfg = replace(Config(nproc_y=2, nproc_x=4, nx=48, ny=24),
                  periodic_x=periodic)
    snaps, _, _ = solve(cfg, 10 * cfg.dt, num_multisteps=5)
    assert np.all(np.isfinite(reassemble(snaps[-2], cfg)))


@pytest.mark.parametrize("grid", [(1, 1), (2, 4)])
@pytest.mark.parametrize("periodic", [True, False])
def test_fast_step_matches_reference_step(grid, periodic):
    """model_step_fast must reproduce model_step field-for-field, on both a
    single-rank and a 2-D decomposition, in both boundary modes.

    Tolerance: the two programs deliberately differ in seam-halo freshness
    around the viscous substep (model_step_fast docstring) — an artifact of
    the same size as the *reference's own* decomposition variance (its
    (1,1)-vs-(2,4) results differ by ~5e-5; see the invariance tests
    below).  A halo-logic bug would produce O(field-scale) errors, far
    above this band."""
    from dataclasses import replace

    from shallow_water import make_mesh_and_comm, make_stepper

    ny_, nx_ = grid
    cfg = replace(
        Config(nproc_y=ny_, nproc_x=nx_, nx=48, ny=24), periodic_x=periodic
    )
    devices = jax.devices()[: cfg.nproc]
    _, comm = make_mesh_and_comm(cfg, devices=devices)
    first_ref, multi_ref = make_stepper(cfg, comm, fast=False)
    first_fast, multi_fast = make_stepper(cfg, comm, fast=True)

    s0 = initial_state(cfg)
    ref = multi_ref(first_ref(s0), 20)
    fast = multi_fast(first_fast(s0), 20)
    # On a (1,1) grid there are no subdomain seams, so the freshness
    # artifact is absent and the remaining divergence is pure
    # reordered-arithmetic rounding, bounded by f32 ulps at the stencil's
    # *intermediate* scale (g·h ≈ 1e3 → ~5e-5 absolute; measured flat from
    # step 1 to 20, i.e. non-accumulating).  Assert a 5×-tighter constant
    # term than the seam band so a small-field regression cannot hide
    # under the loose bound.
    single_rank = grid == (1, 1)
    for name, a, b in zip(ref._fields, ref, fast):
        a, b = np.asarray(a), np.asarray(b)
        if single_rank:
            bound = 2e-5 + 1e-5 * np.abs(a).max()
        else:
            bound = 1e-4 + 1e-5 * np.abs(a).max()
        assert np.abs(a - b).max() <= bound, (
            f"field {name} diverged beyond the freshness band "
            f"(grid={grid}, periodic={periodic}): "
            f"max abs {np.abs(a - b).max():.3e} > {bound:.3e}"
        )


def _pallas_grid_cases():
    """Grid sizes derived from the kernel's block size so coverage tracks
    _PBLK: a partial single block, exactly one full block, exactly two
    full blocks, and full blocks + a partial trailing block — the last
    two exercise the multi-block prev/next margin index maps and their
    clip-at-edge handling, the path the benchmark config (15 blocks) runs."""
    from shallow_water import _PBLK

    return [
        (_PBLK - 8, 48),        # single partial block
        (_PBLK - 2, 48),        # exactly one full block (ny_local == _PBLK)
        (2 * _PBLK - 2, 48),    # exactly two full blocks
        (2 * _PBLK + 14, 40),   # two full + partial trailing, nx_local=42
    ]


@pytest.mark.parametrize("mode,steps", [
    ("pallas2", (10, 11)),  # whole pairs; pair + odd single remainder
    ("pallas3", (9, 11)),   # whole triples; triples + 2-single remainder
])
@pytest.mark.slow
@pytest.mark.parametrize("ny,nx", _pallas_grid_cases())
def test_pallas_chunk_step_matches_fast_steps(ny, nx, mode, steps):
    """The chunk kernels (2 or 3 fused steps per call; margins of 8 rows
    per fused step rounded up to a divisor of _PBLK — 16 for pairs, 32
    for triples) must reproduce model_step_fast over runs that mix the
    single first step, whole chunk calls, and single-step remainders."""
    from shallow_water import make_mesh_and_comm, make_stepper

    cfg = Config(nproc_y=1, nproc_x=1, nx=nx, ny=ny)
    devices = jax.devices()[:1]
    _, comm = make_mesh_and_comm(cfg, devices=devices)
    first_fast, multi_fast = make_stepper(cfg, comm, fast=True)
    first_pal, multi_pal = make_stepper(cfg, comm, fast=mode)

    s0 = initial_state(cfg)
    for nsteps in steps:
        fast = multi_fast(first_fast(s0), nsteps)
        pal = multi_pal(first_pal(s0), nsteps)
        for name, a, b in zip(fast._fields, fast, pal):
            a, b = np.asarray(a), np.asarray(b)
            # pure reordered-arithmetic rounding (verified diffuse across
            # rows, not block-boundary-concentrated): observed max 7.6e-6
            # (h, scale 1e2) / 2.2e-6 (v, scale 5e-2) after 11 steps
            bound = 5e-6 + 1e-6 * np.abs(a).max()
            assert np.abs(a - b).max() <= bound, (
                f"field {name} diverged (ny={ny}, nx={nx}, nsteps={nsteps}): "
                f"max abs {np.abs(a - b).max():.3e} > {bound:.3e}"
            )


@pytest.mark.slow
@pytest.mark.parametrize("ny,nx", _pallas_grid_cases())
def test_pallas_step_matches_fast_step(ny, nx):
    """The fused whole-step Pallas kernel (interpret mode on CPU) must
    reproduce model_step_fast on the single-rank periodic-x configs it is
    restricted to, including row counts that are not multiples of the
    32-row block, at tight tolerance: same elementwise operand values, so
    the only divergence is fusion-order rounding (~1 ulp/step — observed
    max 1.1e-6 after 11 steps), far below the 1e-4 freshness band of the
    fast-vs-reference test."""
    from shallow_water import make_mesh_and_comm, make_stepper

    cfg = Config(nproc_y=1, nproc_x=1, nx=nx, ny=ny)
    devices = jax.devices()[:1]
    _, comm = make_mesh_and_comm(cfg, devices=devices)
    first_fast, multi_fast = make_stepper(cfg, comm, fast=True)
    first_pal, multi_pal = make_stepper(cfg, comm, fast="pallas")

    s0 = initial_state(cfg)
    fast = multi_fast(first_fast(s0), 10)
    pal = multi_pal(first_pal(s0), 10)
    for name, a, b in zip(fast._fields, fast, pal):
        a, b = np.asarray(a), np.asarray(b)
        np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-5,
            err_msg=f"field {name} diverged (ny={ny}, nx={nx})",
        )


def test_pallas_step_rejects_multirank_config():
    from shallow_water import make_mesh_and_comm, make_stepper

    cfg = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    _, comm = make_mesh_and_comm(cfg)
    with pytest.raises(ValueError, match="single-rank periodic-x"):
        first, _ = make_stepper(cfg, comm, fast="pallas")
        first(initial_state(cfg))


def test_select_step_auto_picks_kernel_by_mesh():
    from dataclasses import replace

    from shallow_water import (
        model_step_pallas,
        model_step_pallas_halo,
        model_step_wide,
        select_step,
    )

    # whole-step kernel only where every refresh is an in-register periodic
    # fix; the wide-halo kernel everywhere else, unless the local interior
    # is smaller than its 16-cell exchange depth (then split-phase)
    single = Config(nproc_y=1, nproc_x=1, nx=48, ny=24)
    assert select_step("auto", single) is model_step_pallas
    multi = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)  # 12x12 interior
    assert select_step("auto", multi) is model_step_pallas_halo
    big_multi = Config(nproc_y=2, nproc_x=4, nx=64, ny=32)  # 16x16 interior
    assert select_step("auto", big_multi) is model_step_wide
    walls = replace(single, periodic_x=False)  # 24x48 interior
    assert select_step("auto", walls) is model_step_wide
    small_walls = replace(Config(nproc_y=1, nproc_x=1, nx=48, ny=12),
                          periodic_x=False)
    assert select_step("auto", small_walls) is model_step_pallas_halo


@pytest.mark.slow
@pytest.mark.parametrize("grid", [(1, 1), (2, 4), (2, 2)])
@pytest.mark.parametrize("periodic", [True, False])
def test_wide_step_matches_fast_step(grid, periodic):
    """The communication-avoiding wide-halo path (``wide2``: pair kernel +
    16-deep exchange) must reproduce ``model_step_fast`` on every mesh and
    boundary mode, over a run mixing the single first step, whole pair
    calls, and a single-step remainder (11 steps).  Seam cells recomputed
    in the widened frame use the identical expression tree on the
    identical operand values the owning rank uses, so the only divergence
    is fusion-order (FMA-grouping) rounding from the differently-shaped
    program — ~1 ulp/step, the same class and bound as the single-rank
    chunk-kernel tests (measured worst 0.47x this bound after 11 steps)."""
    from dataclasses import replace

    from shallow_water import make_mesh_and_comm, make_stepper

    ny_, nx_ = grid
    cfg = replace(
        Config(nproc_y=ny_, nproc_x=nx_, nx=64, ny=32), periodic_x=periodic
    )
    devices = jax.devices()[: cfg.nproc]
    _, comm = make_mesh_and_comm(cfg, devices=devices)
    first_fast, multi_fast = make_stepper(cfg, comm, fast=True)
    first_wide, multi_wide = make_stepper(cfg, comm, fast="wide2")

    s0 = initial_state(cfg)
    fast = multi_fast(first_fast(s0), 11)
    wide = multi_wide(first_wide(s0), 11)
    for name, a, b in zip(fast._fields, fast, wide):
        a, b = np.asarray(a), np.asarray(b)
        bound = 5e-6 + 1e-6 * np.abs(a).max()
        assert np.abs(a - b).max() <= bound, (
            f"field {name} diverged (grid={grid}, periodic={periodic}): "
            f"max abs {np.abs(a - b).max():.3e} > {bound:.3e}"
        )


@pytest.mark.slow
def test_wide_step_decomposition_invariance_ulp():
    """Decomposition invariance of the wide-halo path, to ~1 ulp: the
    carried widened frame's shape depends on the decomposition (local
    interior + 2x15 margins), so XLA's FMA grouping can differ between
    the (1,1) and (2,4) programs — unlike the fast/split-phase paths,
    whose per-rank arrays it keeps bit-exact.  Measured: exactly 1 f32
    ulp of the field scale after 20 steps (7.6e-6 at h ~ 100); a halo
    or mask bug would be O(field-scale)."""
    steps = 20
    cfg8 = Config(nproc_y=2, nproc_x=4, nx=64, ny=32)
    s8, _, _ = solve(cfg8, steps * cfg8.dt, num_multisteps=5, fast="wide2")
    cfg1 = Config(nproc_y=1, nproc_x=1, nx=64, ny=32)
    s1, _, _ = solve(cfg1, steps * cfg1.dt, num_multisteps=5, fast="wide2",
                     devices=jax.devices()[:1])
    g8 = reassemble(s8[-2], cfg8)
    g1 = reassemble(s1[-2], cfg1)
    bound = 2e-6 * max(1.0, float(np.abs(g1).max()))
    assert np.abs(g8 - g1).max() <= bound, (
        f"{np.abs(g8 - g1).max():.3e} > {bound:.3e}"
    )


@pytest.mark.slow
def test_wide_fused_driver_matches_fast_end_state():
    """``solve_fused``'s wide modes run a dedicated carried-frame program
    (widen once, margin-band refresh per pair, crop once): its end state
    must match the fast path's fused program over a run with first step,
    whole pairs and a remainder (26 steps; bound scaled for the longer
    accumulation, measured worst 1.01x the 11-step band)."""
    cfg = Config(nproc_y=2, nproc_x=4, nx=64, ny=32)
    t1 = 23 * cfg.dt
    _, n_a, sa = solve_fused(cfg, t1, num_multisteps=5, fast=True,
                             return_state=True)
    _, n_b, sb = solve_fused(cfg, t1, num_multisteps=5, fast="wide2",
                             return_state=True)
    assert n_a == n_b
    for name, a, b in zip(sa._fields, sa, sb):
        a, b = np.asarray(a), np.asarray(b)
        bound = 1e-5 + 2e-6 * np.abs(a).max()
        assert np.abs(a - b).max() <= bound, (
            f"field {name} diverged: {np.abs(a - b).max():.3e} > {bound:.3e}"
        )


@pytest.mark.slow
def test_wide_standalone_step_matches_stepper():
    """The standalone per-step form (``model_step_wide``: exchange + one
    kernel call + crop, at its own exchange depth 8) must agree with the
    carried-frame stepper's first step (depth 16) — same arithmetic on
    differently-sized frames, so up to ~1 ulp of fusion-order rounding."""
    from functools import partial

    import mpi4jax_tpu as mpx
    from shallow_water import (
        make_mesh_and_comm,
        make_stepper,
        model_step_wide,
    )

    cfg = Config(nproc_y=2, nproc_x=4, nx=64, ny=32)
    _, comm = make_mesh_and_comm(cfg)
    s0 = initial_state(cfg)

    @partial(mpx.spmd, comm=comm)
    def one(state):
        return model_step_wide(state, cfg, comm, first_step=True)

    a = make_stepper(cfg, comm, fast="wide2")[0](s0)
    b = one(s0)
    for name, x, y in zip(a._fields, a, b):
        x, y = np.asarray(x), np.asarray(y)
        bound = 5e-6 + 1e-6 * np.abs(x).max()
        assert np.abs(x - y).max() <= bound, (
            f"field {name}: {np.abs(x - y).max():.3e} > {bound:.3e}"
        )


def test_wide_step_rejects_small_interior():
    from shallow_water import make_mesh_and_comm, make_stepper

    cfg = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)  # 12x12 < 16
    _, comm = make_mesh_and_comm(cfg)
    # the carried frame is sized for the pair chunk (exchange depth 16),
    # which a 12-cell interior cannot supply from its immediate neighbor
    first, _ = make_stepper(cfg, comm, fast="wide2")
    with pytest.raises(ValueError, match="local interior"):
        first(initial_state(cfg))


@pytest.mark.slow
@pytest.mark.parametrize("grid", [(1, 1), (2, 4)])
@pytest.mark.parametrize("periodic", [True, False])
def test_pallas_halo_step_matches_fast_step(grid, periodic):
    """The split-phase path (``model_step_pallas_halo``) must reproduce
    ``model_step_fast`` bit-for-bit on every mesh/boundary combination: its
    interpret path evaluates the same window arithmetic (identical
    expression order) on the full local array with the identical exchange
    sequence, so there is no rounding divergence at all."""
    from dataclasses import replace

    from shallow_water import make_mesh_and_comm, make_stepper

    ny_, nx_ = grid
    cfg = replace(
        Config(nproc_y=ny_, nproc_x=nx_, nx=48, ny=24), periodic_x=periodic
    )
    devices = jax.devices()[: cfg.nproc]
    _, comm = make_mesh_and_comm(cfg, devices=devices)
    first_fast, multi_fast = make_stepper(cfg, comm, fast=True)
    first_halo, multi_halo = make_stepper(cfg, comm, fast="pallas_halo")

    s0 = initial_state(cfg)
    fast = multi_fast(first_fast(s0), 12)
    halo = multi_halo(first_halo(s0), 12)
    for name, a, b in zip(fast._fields, fast, halo):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"field {name} diverged (grid={grid}, periodic={periodic})",
        )


def test_pallas_halo_decomposition_invariance_exact():
    """Like the fast step, the split-phase path is exactly decomposition-
    invariant: same bits on one device and on a (2, 4) mesh."""
    steps = 20
    cfg8 = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    s8, _, _ = solve(cfg8, steps * cfg8.dt, num_multisteps=5,
                     fast="pallas_halo")
    cfg1 = Config(nproc_y=1, nproc_x=1, nx=48, ny=24)
    s1, _, _ = solve(cfg1, steps * cfg1.dt, num_multisteps=5,
                     fast="pallas_halo", devices=jax.devices()[:1])
    g8 = reassemble(s8[-2], cfg8)
    g1 = reassemble(s1[-2], cfg1)
    np.testing.assert_array_equal(g8, g1)


def test_fast_step_decomposition_invariance_exact():
    """The fast step's coherent-halo design makes it *exactly*
    decomposition-invariant (the reference's stale-halo seams make its own
    (1,1)-vs-(2,4) runs differ by ~5e-5): same bits on a single device and
    on a (2,4) mesh."""
    steps = 20
    cfg8 = Config(nproc_y=2, nproc_x=4, nx=48, ny=24)
    s8, _, _ = solve(cfg8, steps * cfg8.dt, num_multisteps=5, fast=True)
    cfg1 = Config(nproc_y=1, nproc_x=1, nx=48, ny=24)
    s1, _, _ = solve(cfg1, steps * cfg1.dt, num_multisteps=5, fast=True,
                     devices=jax.devices()[:1])
    g8 = reassemble(s8[-2], cfg8)
    g1 = reassemble(s1[-2], cfg1)
    np.testing.assert_array_equal(g8, g1)


@pytest.mark.slow
@pytest.mark.parametrize("fast", [True, "pallas_halo", "wide2"])
def test_grad_through_full_multistep(fast):
    """Reverse-mode through the WHOLE flagship workload — first step +
    fori_loop multistep with all halo sendrecvs inside — the composition
    analog of the reference's NetKet-grade allreduce acceptance
    (ref tests/collective_ops/test_allreduce.py:254-324): the gradient must
    match finite differences on the (1, 1) mesh and be decomposition-
    invariant on (2, 4).  Runs for both the fused-jnp step and the
    split-phase path (whose interpret form is plain differentiable jnp)."""
    from shallow_water import make_mesh_and_comm, make_stepper

    steps = 6
    # wide2 needs a 16-cell local interior on the (2, 4) mesh
    gny, gnx = (32, 64) if fast == "wide2" else (8, 16)
    # ONE decomposition-independent perturbation field, shared by both mesh
    # configurations (drawn once — the gradients can only be compared if
    # both losses perturb the same global field)
    bump_global = np.random.RandomState(0).randn(gny + 2, gnx + 2).astype(
        np.float32)

    def make_loss(cfg):
        devices = jax.devices()[: cfg.nproc]
        _, comm = make_mesh_and_comm(cfg, devices=devices)
        first, multi = make_stepper(cfg, comm, fast=fast)
        s0 = initial_state(cfg)

        def cut(arr):
            blocks = []
            sy, sx = cfg.ny_local - 2, cfg.nx_local - 2
            for py in range(cfg.nproc_y):
                for px in range(cfg.nproc_x):
                    blocks.append(arr[py * sy:py * sy + cfg.ny_local,
                                      px * sx:px * sx + cfg.nx_local])
            return jnp.asarray(np.stack(blocks))

        bump = cut(bump_global)

        def loss(amp):
            state = s0._replace(h=s0.h + amp * bump)
            state = multi(first(state), steps)
            # interior-only: stacked interiors tile the global domain
            # disjointly, so the loss is decomposition-invariant
            inner = state.h[:, 1:-1, 1:-1]
            return jnp.sum((inner - 100.0) ** 2)

        return loss

    cfg1 = Config(nproc_y=1, nproc_x=1, nx=gnx, ny=gny)
    loss1 = make_loss(cfg1)
    g1 = jax.grad(loss1)(0.0)

    # finite differences (f32: central difference at a scale-matched eps)
    eps = 1e-2
    fd = (loss1(eps) - loss1(-eps)) / (2 * eps)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(fd), rtol=2e-2)

    cfg8 = Config(nproc_y=2, nproc_x=4, nx=gnx, ny=gny)
    g8 = jax.grad(make_loss(cfg8))(0.0)
    # the fast path is exactly decomposition-invariant, so its gradient is
    # too (up to f32 reduction-order rounding in the loss sum)
    np.testing.assert_allclose(np.asarray(g8), np.asarray(g1), rtol=1e-4)


def test_long_context_training_matches_single_device_grads():
    """The dp x sp training example: the distributed step's allreduced
    loss and parameter update must match a single-device model run on the
    gathered batch/sequence with full attention — the end-to-end pin that
    sequence-parallel training (ring attention under value_and_grad,
    world-allreduced gradients) is exact, not approximate."""
    from long_context_training import (
        block_forward, init_params, make_train_step,
    )

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.attention import reference_attention

    n, n_dp, n_sp = 8, 2, 4
    mesh = mpx.make_world_mesh((n_dp, n_sp), ("dp", "sp"))
    world = mpx.Comm(("dp", "sp"), mesh=mesh)
    sp = world.sub("sp")

    b_loc, t_loc, d_model, d_ff, heads = 1, 16, 32, 64, 4
    lr = 0.05
    params = init_params(jax.random.PRNGKey(0), d_model, d_ff)
    params_g = {k: jnp.broadcast_to(v, (n, *v.shape))
                for k, v in params.items()}
    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(kx, (n, b_loc, t_loc, d_model), jnp.float32)
    y = jax.random.normal(ky, (n, b_loc, t_loc), jnp.float32)

    step = make_train_step(world, sp, heads, lr=lr)
    new_params, loss = step(params_g, x, y)

    # single-device reference: rank r = dp * n_sp + sp holds batch row dp,
    # sequence chunk sp — gather to (n_dp * b_loc, T_global, ...)
    def gather(a):
        rows = [jnp.concatenate([a[dp * n_sp + s] for s in range(n_sp)],
                                axis=1) for dp in range(n_dp)]
        return jnp.concatenate(rows, axis=0)

    xg, yg = gather(x), gather(y)

    def loss_full(p):
        pred = block_forward(
            p, xg, heads=heads,
            attend=lambda q, k, v: reference_attention(q, k, v, causal=True),
        )
        return jnp.mean((pred - yg) ** 2)

    l_full, g_full = jax.value_and_grad(loss_full)(params)
    np.testing.assert_allclose(
        float(jnp.asarray(loss)[0]), float(l_full), rtol=1e-5)
    for name in params:
        g_dist = (np.asarray(params_g[name][0])
                  - np.asarray(new_params[name][0])) / lr
        np.testing.assert_allclose(
            g_dist, np.asarray(g_full[name]), rtol=2e-3, atol=2e-5,
            err_msg=f"grad {name}",
        )
