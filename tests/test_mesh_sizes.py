"""Degenerate and small mesh sizes + vmap coverage beyond allreduce.

The reference runs its whole suite in BOTH 1-process and N-process modes
(ref docs/developers.rst:15-27): collectives on 1 process degenerate to
self-communication and must still work.  The analog here is running the
ops over 1-, 2-, and 8-device meshes of the same virtual CPU pool.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx


def _comm(n):
    mesh = mpx.make_world_mesh(devices=jax.devices()[:n])
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


@pytest.mark.parametrize("n", [1, 2, 8])
def test_collectives_all_sizes(n):
    comm = _comm(n)
    x = jnp.arange(float(n))[:, None] + 1.0

    @mpx.spmd(comm=comm)
    def f(x):
        a, tok = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        b, tok = mpx.allgather(x, comm=comm, token=tok)
        c, tok = mpx.bcast(x, 0, comm=comm, token=tok)
        d, tok = mpx.scan(x, mpx.SUM, comm=comm, token=tok)
        e, tok = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm, token=tok)
        mpx.barrier(comm=comm, token=tok)
        return a, b.sum(0), c, d, e

    a, b, c, d, e = (np.asarray(v).ravel() for v in f(x))
    total = np.arange(1.0, n + 1).sum()
    assert (a == total).all()
    assert (b == total).all()
    assert (c == 1.0).all()                       # root 0's value everywhere
    np.testing.assert_allclose(d, np.cumsum(np.arange(1.0, n + 1)))
    np.testing.assert_allclose(e, np.roll(np.arange(1.0, n + 1), 1))


@pytest.mark.parametrize("n", [1, 2])
def test_ring_self_communication(n):
    """shift(1) on a size-n ring: on 1 device the permute is a self-send
    (the reference's 1-process self-communication mode)."""
    comm = _comm(n)

    @mpx.spmd(comm=comm)
    def f(x):
        r, _ = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm)
        return r

    x = jnp.arange(float(n))[:, None]
    out = np.asarray(f(x)).ravel()
    np.testing.assert_array_equal(out, np.roll(np.arange(float(n)), 1))


def test_complex_and_bool_collectives():
    """Dtype parity with the reference's MPI_TYPE_MAP (ref
    _src/utils.py:100-115): complex and bool ride the collectives."""
    comm = _comm(8)

    @mpx.spmd(comm=comm)
    def f(z, m):
        zs, tok = mpx.allreduce(z, op=mpx.SUM, comm=comm)
        ms, _ = mpx.allreduce(m, op=mpx.LOR, comm=comm, token=tok)
        return zs, ms

    z = (jnp.arange(8.0) + 1j * jnp.arange(8.0))[:, None].astype(jnp.complex64)
    m = (jnp.arange(8) == 3)[:, None]
    zs, ms = f(z, m)
    assert np.asarray(zs).ravel()[0] == 28 + 28j
    assert np.asarray(ms).all()


def test_vmap_over_sendrecv():
    comm = _comm(8)

    @mpx.spmd(comm=comm)
    def f(x):
        # batched halo rotation: vmap over the leading batch dim of the
        # rank-local array
        def one(v):
            r, _ = mpx.sendrecv(v, v, dest=mpx.shift(1), comm=comm)
            return r

        return jax.vmap(one)(x)

    x = jnp.arange(8.0 * 3).reshape(8, 3, 1)  # (ranks, batch, 1)
    out = np.asarray(f(x))
    expected = np.roll(np.asarray(x), 1, axis=0)
    np.testing.assert_array_equal(out, expected)


def test_vmap_over_gather_and_bcast():
    comm = _comm(8)

    @mpx.spmd(comm=comm)
    def f(x):
        def one(v):
            g, tok = mpx.gather(v, 0, comm=comm)
            b, _ = mpx.bcast(v, 3, comm=comm, token=tok)
            return g.sum(0), b

        return jax.vmap(one)(x)

    x = jnp.arange(8.0 * 2).reshape(8, 2, 1)
    s, b = f(x)
    xs = np.asarray(x)
    np.testing.assert_array_equal(np.asarray(s), np.broadcast_to(
        xs.sum(0, keepdims=True), xs.shape))
    np.testing.assert_array_equal(np.asarray(b), np.broadcast_to(
        xs[3:4], xs.shape))


def test_hybrid_ensemble_spatial_mesh():
    """Parallelism composition on ONE 3-axis mesh (dp, py, px): an ensemble
    of spatially-decomposed shallow-water members steps on the ("py", "px")
    sub-communicator while ensemble statistics allreduce over the
    orthogonal "dp" axis — the sp x dp hybrid a pod would run."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    from shallow_water import Config, State, initial_state, model_step_fast

    mesh = mpx.make_world_mesh((2, 2, 2), ("dp", "py", "px"))
    world = mpx.Comm(("dp", "py", "px"), mesh=mesh)
    sp = world.sub("py", "px")
    dpc = world.sub("dp")

    cfg = Config(nproc_y=2, nproc_x=2, nx=16, ny=8)
    s0 = initial_state(cfg)  # (4, ny_l, nx_l) spatial blocks

    def ensemble(field, delta):
        # world-global (8, ...) array, dp-major: member 0, then member 1
        return jnp.concatenate([field, field + delta], axis=0)

    h = ensemble(s0.h, 0.1)  # member 1 starts 10 cm higher everywhere
    u, v, dh, du, dv = (ensemble(f, 0.0) for f in (s0.u, s0.v, s0.dh,
                                                   s0.du, s0.dv))

    @mpx.spmd(comm=world)
    def run(h, u, v, dh, du, dv):
        state = State(h, u, v, dh, du, dv)
        state = model_step_fast(state, cfg, sp, first_step=True)
        state = model_step_fast(state, cfg, sp, first_step=False)
        total, _ = mpx.allreduce(state.h, op=mpx.SUM, comm=dpc)
        return state.h, total * 0.5

    h_out, mean = run(h, u, v, dh, du, dv)
    h_out, mean = np.asarray(h_out), np.asarray(mean)
    assert np.isfinite(h_out).all()
    # the dp-allreduce pairs ranks differing only in their dp coordinate:
    # spatial block i of member 0 is rank i, of member 1 rank i + 4
    for i in range(4):
        want = 0.5 * (h_out[i] + h_out[i + 4])
        np.testing.assert_allclose(mean[i], want, rtol=1e-6)
        np.testing.assert_allclose(mean[i + 4], want, rtol=1e-6)
    # members stay distinct dynamical trajectories
    assert np.abs(h_out[:4] - h_out[4:]).max() > 1e-3


@pytest.mark.parametrize("n", [3, 5, 7])
def test_butterfly_allreduce_odd_sizes(n):
    """The doubling butterfly's window clamping at non-power-of-2 sizes:
    PROD (no native collective) and a non-commutative matmul must both
    give the ascending-rank fold on every rank."""
    comm = _comm(n)

    @mpx.spmd(comm=comm)
    def f(x, m):
        p, tok = mpx.allreduce(x, op=mpx.PROD, comm=comm)
        mm, _ = mpx.allreduce(m, op=jnp.matmul, comm=comm, token=tok)
        return p, mm

    vals = 1.0 + jnp.arange(n)[:, None] / 8.0
    rng = np.random.default_rng(n)
    mats = jnp.asarray(rng.normal(size=(n, 2, 2)).astype(np.float32))
    p, mm = f(vals, mats)
    np.testing.assert_allclose(
        np.asarray(p)[:, 0], np.prod(np.asarray(vals)), rtol=1e-6)
    expected = np.eye(2, dtype=np.float32)
    for r in range(n):
        expected = expected @ np.asarray(mats)[r]
    for r in range(n):
        np.testing.assert_allclose(np.asarray(mm)[r], expected,
                                   rtol=1e-5, atol=1e-5)
