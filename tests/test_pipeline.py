"""Traced half of the pipeline schedule suite (docs/pipeline.md):
real 8-device rounds through ``mpx.pipeline`` on the virtual CPU mesh.

- every schedule (gpipe / 1f1b / interleaved / auto) bit-identical to
  the sequential single-device reference — the eager phase driver AND
  ``PipelineProgram.trace`` composed inside an existing region (whose
  1F1B steady window compiles through the megastep ``fori_loop``);
- the async p2p primitives inside megastep loops: a wildcard
  ``recv_start(source=None)`` ring under ``unroll=N`` matches N eager
  steps bit for bit and analyzes clean (the PR 7 FIFO-adoption rule at
  exactly the spot 1F1B steady state lives), while a send span with no
  wait in the iteration is MPX130;
- MPX144 through ``mpx.analyze(cost=True)``: a forced ``gpipe`` round
  at a 1f1b-favored shape fires the mispick advisory citing both bubble
  fractions; the 1f1b round at the same shape stays quiet;
- the eager phase driver's host telemetry: ``pipeline.stage`` /
  ``pipeline.bubble_wait`` brackets, the ``pipeline.*_us`` meters, and
  the measured "bubble fraction" line in ``telemetry.report()``.

The pure half (schedule programs, stash bounds, wall-time formulas,
``build_schedule`` p2p roles, the MPX144 checker on hand-built
schedules) runs under any JAX in tests/test_pipeline_pure.py via the
isolated loader.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.parallel.pipeline import split_microbatches
from mpi4jax_tpu.resilience import elastic as el
from mpi4jax_tpu.resilience import runtime as resilience_runtime

UNROLL = 4
DIM = 4
MICRO = 16  # microbatches: > stages, so the flat schedules have a
            # steady window for the megastep compiler to own


@pytest.fixture(autouse=True)
def _clean_state():
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    yield
    mpx.set_telemetry_mode(None)
    mpx.set_analyze_mode(None)
    mpx.set_fusion_mode(None)
    resilience_runtime.reset_overrides()
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    from mpi4jax_tpu.parallel import region as _region

    _region._default_comm = None


def _world_comm():
    mesh = mpx.make_world_mesh()
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def _substage(h, w):
    return jnp.tanh(h @ w)


def _reference(x0, ws_flat, m):
    """Sequential single-device model: every substage in order, applied
    per-microbatch so the pipelined variants (which compute on
    microbatch-sized slices) pin bit-identical, not just allclose."""
    mbs = split_microbatches(x0, m)
    outs = []
    for i in range(m):
        h = mbs[i]
        for k in range(ws_flat.shape[0]):
            h = _substage(h, ws_flat[k])
        outs.append(h)
    return np.asarray(jnp.stack(outs))


def _problem(comm, virtual=1):
    """A (stages * virtual)-substage model + its global pipeline view:
    ``mbs`` is ``(S, M, mb, DIM)`` with stage 0's row real, ``ws`` is
    rank r's substage stack (chunk c of rank r = substage c*S + r)."""
    s = comm.Get_size()
    rng = np.random.default_rng(7)
    x0 = jnp.asarray(rng.normal(size=(MICRO, DIM)), jnp.float32)
    ws_flat = jnp.asarray(rng.normal(size=(s * virtual, DIM, DIM)) * 0.5,
                          jnp.float32)
    mbs = jnp.zeros((s, MICRO, 1, DIM), jnp.float32).at[0].set(
        split_microbatches(x0, MICRO))
    if virtual == 1:
        ws = ws_flat
    else:
        ws = ws_flat.reshape(virtual, s, DIM, DIM).transpose(1, 0, 2, 3)
    want = _reference(x0, ws_flat, MICRO)
    return mbs, ws, want


def _check(prog, mbs, ws, want, label):
    got = np.asarray(prog(mbs, ws))
    np.testing.assert_array_equal(
        got[-1], want,
        err_msg=f"schedule {label!r} diverged from the reference")


# ---------------------------------------------------------------------------
# schedule bit-identity: eager phase driver vs the sequential reference
# ---------------------------------------------------------------------------


def test_gpipe_matches_sequential_reference():
    comm = _world_comm()
    mbs, ws, want = _problem(comm)
    prog = mpx.pipeline(_substage, MICRO, schedule="gpipe", comm=comm)
    _check(prog, mbs, ws, want, "gpipe")


def test_1f1b_matches_sequential_reference():
    comm = _world_comm()
    mbs, ws, want = _problem(comm)
    # megastep on (the default): the steady window is one fori_loop
    # dispatch, every send_start/recv_start/p2p_wait span inside one
    # iteration
    prog = mpx.pipeline(_substage, MICRO, schedule="1f1b", comm=comm)
    plan = prog.plan(comm.Get_size(), MICRO, DIM * 4)
    assert plan.steady == MICRO - (comm.Get_size() - 1)
    _check(prog, mbs, ws, want, "1f1b")


def test_1f1b_megastep_off_is_bit_identical_too():
    comm = _world_comm()
    mbs, ws, want = _problem(comm)
    prog = mpx.pipeline(_substage, MICRO, schedule="1f1b", comm=comm,
                        megastep=False)
    _check(prog, mbs, ws, want, "1f1b[megastep=False]")


def test_interleaved_virtual2_matches_sequential_reference():
    comm = _world_comm()
    mbs, ws, want = _problem(comm, virtual=2)
    prog = mpx.pipeline(_substage, MICRO, schedule="interleaved",
                        virtual=2, comm=comm)
    _check(prog, mbs, ws, want, "interleaved")


def test_auto_resolves_through_cost_model_and_matches_reference():
    comm = _world_comm()
    mbs, ws, want = _problem(comm)
    prog = mpx.pipeline(_substage, MICRO, comm=comm)  # schedule='auto'
    plan = prog.plan(comm.Get_size(), MICRO, DIM * 4)
    assert plan.schedule in ("gpipe", "1f1b")  # resolved, never 'auto'
    _check(prog, mbs, ws, want, "auto")


def test_multi_fn_chunked_stages_interleaved_and_auto():
    # a per-chunk stage_fns LIST (not a single fn over chunk-axis
    # params): only interleaved can express it, and every chunk must
    # actually run — the truncation guard's positive twin
    comm = _world_comm()
    mbs, ws, want = _problem(comm, virtual=2)
    fns = [lambda h, p: _substage(h, p[0]),
           lambda h, p: _substage(h, p[1])]
    prog = mpx.pipeline(fns, MICRO, schedule="interleaved", comm=comm)
    _check(prog, mbs, ws, want, "interleaved[fns]")
    # schedule='auto' restricts the candidate set to what the chunked
    # program expresses, so it can only resolve to interleaved
    auto_prog = mpx.pipeline(fns, MICRO, comm=comm)
    plan = auto_prog.plan(comm.Get_size(), MICRO, DIM * 4)
    assert plan.schedule == "interleaved" and plan.virtual == 2
    _check(auto_prog, mbs, ws, want, "auto[fns]")


def test_multi_fn_non_interleaved_schedule_rejected():
    # gpipe/1f1b over a chunked program would silently compute a
    # truncated model (only chunk 0 applied); the builder refuses
    fns = [lambda h, p: _substage(h, p[0]),
           lambda h, p: _substage(h, p[1])]
    for schedule in ("gpipe", "1f1b"):
        with pytest.raises(ValueError, match="stage-chunks"):
            mpx.pipeline(fns, MICRO, schedule=schedule)
        with pytest.raises(ValueError, match="stage-chunks"):
            mpx.pipeline(_substage, MICRO, schedule=schedule, virtual=2)


def test_trace_composes_inside_region():
    comm = _world_comm()
    mbs, ws, want = _problem(comm)
    prog = mpx.pipeline(_substage, MICRO, schedule="1f1b", comm=comm)

    @mpx.spmd(comm=comm)
    def round_fn(m, w):
        out, _tok = prog.trace(m, w)
        return out

    got = np.asarray(round_fn(mbs, ws))
    np.testing.assert_array_equal(got[-1], want)
    # and the composed round is analyzer-clean: every p2p span opens
    # and closes inside one steady-loop iteration
    report = mpx.analyze(round_fn, mbs, ws)
    bad = [f for f in report.findings
           if f.code in ("MPX112", "MPX130")]
    assert not bad, report.render()


# ---------------------------------------------------------------------------
# async p2p inside megastep loops: wildcard adoption + span rules
# ---------------------------------------------------------------------------


def _ring_step(comm):
    n = comm.Get_size()
    ring = tuple(((i, (i + 1) % n)) for i in range(n))

    def step(v):
        # send_start queues the payload; the wildcard recv_start
        # (source=None) adopts the queued send's ring routing — the
        # exact FIFO-adoption rule 1F1B steady state leans on
        sh, tok = mpx.send_start(v, ring)
        rh, tok = mpx.recv_start(v, token=tok)
        got, tok = mpx.p2p_wait(rh, token=tok)
        _, tok = mpx.p2p_wait(sh, token=tok)
        return got * 0.5 + v * 0.25

    return step


def test_wildcard_recv_adoption_inside_megastep_bit_identity():
    comm = _world_comm()
    k = comm.Get_size()
    step = _ring_step(comm)
    x = jnp.arange(k * DIM, dtype=jnp.float32).reshape(k, DIM) * 0.125

    out = x
    eager = mpx.spmd(step, comm=comm)
    for _ in range(UNROLL):
        out = eager(out)
    want = np.asarray(out)

    pinned = mpx.spmd(step, comm=comm, unroll=UNROLL)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_p2p_spans_inside_megastep_analyze_clean():
    comm = _world_comm()
    step = _ring_step(comm)
    k = comm.Get_size()
    x = jnp.ones((k, DIM), jnp.float32)
    report = mpx.analyze(mpx.spmd(step, comm=comm, unroll=UNROLL), x)
    assert not any(f.code in ("MPX112", "MPX130") for f in
                   report.findings), report.render()


def test_p2p_span_straddling_megastep_boundary_is_mpx130():
    comm = _world_comm()
    n = comm.Get_size()
    ring = tuple(((i, (i + 1) % n)) for i in range(n))

    def straddling(v):
        # a send span opened in the iteration with no p2p_wait: the
        # span straddles the loop boundary by construction
        _sh, _tok = mpx.send_start(v, ring)
        return mpx.varying(v * 1.0)

    x = jnp.ones((n, DIM), jnp.float32)
    bad = mpx.spmd(straddling, comm=comm, unroll=UNROLL)
    report = mpx.analyze(bad, x)
    assert any(f.code == "MPX130" for f in report.findings), \
        report.render()


# ---------------------------------------------------------------------------
# MPX144: the schedule-mispick advisory end to end
# ---------------------------------------------------------------------------

# 8 stages x 8 microbatches x 64 KiB boundary payload: the cost model
# prices gpipe >10% over 1f1b there (tests/test_pipeline_pure.py pins
# the formula-level margin), so a forced gpipe round is a mispick.
_MISPICK_M = 8
_MISPICK_MB, _MISPICK_DIM = 64, 256  # 64 * 256 * 4 B = 64 KiB


def _mispick_round(comm, schedule):
    prog = mpx.pipeline(_substage, _MISPICK_M, schedule=schedule,
                        comm=comm)

    def round_fn(m, w):
        out, _tok = prog.trace(m, w)
        return out

    return round_fn


def _mispick_analyze(comm, schedule):
    # abstract templates: analyze re-traces, nothing executes, so the
    # 64 KiB-per-boundary shape costs no memory
    s = comm.Get_size()
    mbs = jax.ShapeDtypeStruct(
        (s, _MISPICK_M, _MISPICK_MB, _MISPICK_DIM), jnp.float32)
    ws = jax.ShapeDtypeStruct((s, _MISPICK_DIM, _MISPICK_DIM),
                              jnp.float32)
    return mpx.analyze(_mispick_round(comm, schedule), mbs, ws,
                       comm=comm, ranks="all", cost=True)


def test_mpx144_fires_on_mispicked_gpipe_round():
    comm = _world_comm()
    report = _mispick_analyze(comm, "gpipe")
    hits = [f for f in report.findings if f.code == "MPX144"]
    assert hits, report.render()
    f = hits[0]
    assert "'gpipe'" in f.message
    assert "'1f1b'" in f.message
    assert "bubble fraction" in f.message
    assert "schedule='auto'" in f.suggestion
    from mpi4jax_tpu.analysis import CODES

    assert CODES["MPX144"].severity == "advisory"


def test_mpx144_quiet_when_the_schedule_is_the_argmin():
    comm = _world_comm()
    report = _mispick_analyze(comm, "1f1b")
    assert not any(f.code == "MPX144" for f in report.findings), \
        report.render()


# ---------------------------------------------------------------------------
# telemetry: phase brackets, meters, and the measured bubble fraction
# ---------------------------------------------------------------------------


def test_eager_phases_meter_the_bubble_and_report_renders_it():
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    try:
        comm = _world_comm()
        mbs, ws, want = _problem(comm)
        prog = mpx.pipeline(_substage, MICRO, schedule="1f1b", comm=comm)
        got = prog(mbs, ws)
        jax.block_until_ready(got)
        np.testing.assert_array_equal(np.asarray(got)[-1], want)

        snap = mpx.telemetry.snapshot()
        meters = snap["meters"]
        assert meters.get("pipeline.rounds", 0) >= 1, meters
        assert meters.get("pipeline.stage_us", 0) > 0, meters
        assert "pipeline.bubble_wait_us" in meters, meters

        from mpi4jax_tpu.telemetry.core import op_key

        stage_key = op_key("pipeline.stage", comm.uid, "1f1b", "")
        wait_key = op_key("pipeline.bubble_wait", comm.uid, "1f1b", "")
        assert snap["ops"][stage_key]["calls"] == 1, snap["ops"].keys()
        # warmup + cooldown: two bubble_wait dispatches per round
        assert snap["ops"][wait_key]["calls"] == 2

        from mpi4jax_tpu.telemetry import report as treport

        text = treport.render([snap])
        assert "pipeline:" in text
        assert "bubble fraction" in text
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


def test_telemetry_off_adds_no_pipeline_meters():
    mpx.telemetry.reset()
    comm = _world_comm()
    mbs, ws, _want = _problem(comm)
    prog = mpx.pipeline(_substage, MICRO, schedule="gpipe", comm=comm)
    jax.block_until_ready(prog(mbs, ws))
    snap = mpx.telemetry.snapshot()
    assert not any(k.startswith("pipeline.") for k in snap["meters"]), \
        snap["meters"]
