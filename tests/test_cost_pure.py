"""Static cost model + critic: the pure-Python half (docs/analysis.md
"Cost model").

The formula matrix (13 ops x {ring, butterfly, vdg, hier} x link
classes), the alpha-beta-gamma time arithmetic, tuning-file
parse/accept/reject, the critical-path timing simulation on scripted
schedules, and the MPX131-MPX135 positive/negative matrix — all loaded
under a private package name (the tests/test_analysis_pure.py isolated
loader) so everything here runs even where the installed JAX is below
the package's floor.  The traced integration half — cost=True through
``mpx.analyze`` and the ambient env path on the real 8-device mesh —
lives in tests/test_cost.py.
"""

import importlib
import json
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_cost_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "ops", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    # ops._algos / ops._hierarchy import jax.numpy (importable on any
    # JAX) — they are the pinned byte models the cost formulas reuse
    for mod in ("utils.config", "ops._fusion", "ops._algos",
                "ops._hierarchy", "analysis.report", "analysis.graph",
                "analysis.checkers", "analysis.schedule",
                "analysis.matcher", "analysis.progress",
                "analysis.costmodel", "analysis.cost",
                "parallel.topology"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
cost = sys.modules[f"{_ISO_NAME}.analysis.cost"]
sched = sys.modules[f"{_ISO_NAME}.analysis.schedule"]
matcher = sys.modules[f"{_ISO_NAME}.analysis.matcher"]
algos = sys.modules[f"{_ISO_NAME}.ops._algos"]
hierarchy = sys.modules[f"{_ISO_NAME}.ops._hierarchy"]
topology = sys.modules[f"{_ISO_NAME}.parallel.topology"]

S = sched.SchedOp
MODEL = cm.CostModel()


def t_us(c):
    return MODEL.time_us(c)


# ---------------------------------------------------------------------------
# the formula matrix: rounds + bytes per link class, all 13 ops
# ---------------------------------------------------------------------------

N = 8192  # payload bytes; k = 8 -> chunk = 1024
K = 8
CHUNK = 1024


def test_allreduce_butterfly_single_and_multi_host():
    c = cm.collective_cost("allreduce", "butterfly", N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (6, 6 * N)
    assert not c.dcn and c.gamma_bytes == N
    # multi-host flat: every round gated on DCN (the MPX113 hazard)
    c = cm.collective_cost("allreduce", "butterfly", N, K, hosts=2)
    assert (c.dcn.rounds, c.dcn.nbytes) == (6, 6 * N)
    assert not c.ici


def test_allreduce_ring_and_order_preserving_pair():
    c = cm.collective_cost("allreduce", "ring", N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (14, 7 * CHUNK * 2)
    cp = cm.collective_cost("allreduce", "ring", N, K, preserve=True)
    assert cp.ici.nbytes == 7 * CHUNK * 3  # lo/hi accumulator pair
    # bytes agree with the pinned algorithmic model
    assert c.ici.nbytes == algos.algorithm_bytes_per_rank("ring", N, K)


def test_reduce_prices_like_allreduce():
    a = cm.collective_cost("allreduce", "ring", N, K)
    r = cm.collective_cost("reduce", "ring", N, K)
    assert (r.ici, r.dcn, r.gamma_bytes) == (a.ici, a.dcn, a.gamma_bytes)


def test_reduce_scatter_ring_butterfly():
    c = cm.collective_cost("reduce_scatter", "ring", N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (7, 7 * CHUNK)
    c = cm.collective_cost("reduce_scatter", "butterfly", N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (6, 6 * N)
    assert c.gamma_bytes == N


def test_bcast_doubling_and_vdg():
    c = cm.collective_cost("bcast", "butterfly", N, K)  # doubling
    assert (c.ici.rounds, c.ici.nbytes) == (3, 3 * N)
    c = cm.collective_cost("bcast", "ring", N, K)  # van de Geijn
    assert (c.ici.rounds, c.ici.nbytes) == (3 + 7, N + 7 * CHUNK)
    assert c.gamma_bytes == 0  # no fold in a broadcast


@pytest.mark.parametrize("kind", ["allreduce", "reduce_scatter", "bcast"])
def test_hier_bytes_reuse_the_pinned_models(kind):
    h, r = 2, 4
    c = cm.collective_cost(kind, "hier", N, K, hosts=h, hier=(h, r))
    intra_b, inter_b = hierarchy.hier_link_bytes(kind, N, h, r)
    assert (c.ici.nbytes, c.dcn.nbytes) == (intra_b, inter_b)
    assert c.ici.rounds > 0 and c.dcn.rounds > 0


def test_hier_allreduce_rounds():
    # 2 hosts x 4 ranks: intra ring rs+ag = 2*(r-1) = 6 ICI rounds;
    # the 2048 B shard is far below the DCN crossover -> butterfly
    # inter phase, 2*ceil(log2 2) = 2 DCN rounds
    c = cm.collective_cost("allreduce", "hier", N, K, hosts=2, hier=(2, 4))
    assert c.ici.rounds == 6
    assert c.dcn.rounds == 2


def test_dcn_algo_rule_matches_algos():
    # the local restatement must never drift from resolve_dcn_algo
    for shard in (1 << 10, 1 << 22, 1 << 23, 1 << 24):
        for h in (2, 4, 8):
            for ring_ok in (True, False):
                assert cm._dcn_algo(shard, h, ring_ok) == \
                    algos.resolve_dcn_algo(shard, h, ring_ok)


def test_hier_alltoall_formula_rows():
    # the two-level alltoall: intra transpose (r-1 rounds of size/r
    # blocks over ICI) + inter exchange of host-aggregated blocks (h-1
    # rounds of size/h over DCN) — bytes reused from the pinned byte
    # model so cost and lowering can never drift
    for h, r in ((2, 4), (4, 2), (8, 1)):
        k = h * r
        c = cm.collective_cost("alltoall", "hier", N, k, hosts=h,
                               hier=(h, r))
        intra_b, inter_b = hierarchy.hier_link_bytes("alltoall", N, h, r)
        assert (c.ici.rounds, c.ici.nbytes) == \
            (r - 1 if r > 1 else 0, intra_b)
        assert (c.dcn.rounds, c.dcn.nbytes) == (h - 1, inter_b)
        assert c.gamma_bytes == 0  # a permutation folds nothing
    # flat multi-host: every round gated on DCN (the MPX137 shape)
    c = cm.collective_cost("alltoall", "native", N, K, hosts=2)
    assert (c.dcn.rounds, c.dcn.nbytes) == (7, 7 * CHUNK)
    assert not c.ici
    # the 2x4 time comparison the replay artifact commits: fewer DCN
    # rounds AND fewer DCN bytes make hier strictly faster here
    flat = cm.collective_cost("alltoall", "native", N, 8, hosts=2)
    hier = cm.collective_cost("alltoall", "hier", N, 8, hosts=2,
                              hier=(2, 4))
    assert t_us(hier) < t_us(flat)


def test_chunked_async_formula_rows():
    # the C-chunk async split: bytes invariant, C-1 extra pipeline-fill
    # rounds per active link; C=1 is the identity
    base = cm.collective_cost("alltoall", "hier", N, 8, hosts=2,
                              hier=(2, 4))
    assert cm.chunked_async_cost(base, 1) is base
    split = cm.chunked_async_cost(base, 4)
    assert split.ici.nbytes == base.ici.nbytes
    assert split.dcn.nbytes == base.dcn.nbytes
    assert split.ici.rounds == base.ici.rounds + 3
    assert split.dcn.rounds == base.dcn.rounds + 3
    # inactive links stay inactive (no phantom fill rounds)
    p2p = cm.p2p_cost(N, same_host=True)
    split = cm.chunked_async_cost(p2p, 2)
    assert not split.dcn and split.ici.rounds == 2
    # the fill is pure alpha: the time delta is exactly (C-1) rounds
    assert t_us(cm.chunked_async_cost(base, 4)) == pytest.approx(
        t_us(base) + 3 * (MODEL.params["links"]["ici"]["alpha_us"]
                          + MODEL.params["links"]["dcn"]["alpha_us"]))


def test_best_algo_alltoall_candidates():
    model = cm.CostModel()
    # multi-host, hier expressible: the model prefers the two-level
    # split once the payload is DCN-round-bound
    best, times = cm.best_algo("alltoall", 1 << 20, 8, model, hosts=2,
                               hier=(2, 4))
    assert set(times) == {"native", "hier"}
    assert best == "hier"
    # no hierarchy: flat is the only candidate
    best, times = cm.best_algo("alltoall", 1 << 20, 8, model)
    assert set(times) == {"native"} and best == "native"


def test_remaining_collectives():
    c = cm.collective_cost("allgather", None, N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (7, 7 * N)
    c = cm.collective_cost("alltoall", None, N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (7, 7 * CHUNK)
    c = cm.collective_cost("gather", None, N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (3, 7 * N)
    c = cm.collective_cost("scatter", None, N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (3, 7 * CHUNK)
    c = cm.collective_cost("scan", None, N, K)
    assert (c.ici.rounds, c.ici.nbytes) == (3, 3 * N)
    assert c.gamma_bytes == N
    c = cm.collective_cost("barrier", None, 0, K)
    assert (c.ici.rounds, c.ici.nbytes) == (3, 0)
    # multi-host attribution for the canonical models
    c = cm.collective_cost("allgather", None, N, K, hosts=2)
    assert c.dcn.rounds == 7 and not c.ici


def test_p2p_and_degenerate_cases():
    c = cm.p2p_cost(N, same_host=True)
    assert (c.ici.rounds, c.ici.nbytes) == (1, N)
    c = cm.p2p_cost(N, same_host=False)
    assert (c.dcn.rounds, c.dcn.nbytes) == (1, N)
    assert cm.collective_cost("allreduce", "ring", N, 1) is cm.ZERO_COST
    with pytest.raises(ValueError, match="point-to-point"):
        cm.collective_cost("send", None, N, K)
    with pytest.raises(ValueError, match="unmodeled"):
        cm.collective_cost("frobnicate", None, N, K)


def test_every_public_op_is_modeled():
    for op in cm.MODELED_OPS:
        if op in ("send", "recv", "sendrecv"):
            assert t_us(cm.p2p_cost(N)) > 0
        elif op == "barrier":
            assert t_us(cm.collective_cost(op, None, 0, K)) > 0
        else:
            assert t_us(cm.collective_cost(op, None, N, K)) > 0


# ---------------------------------------------------------------------------
# time arithmetic + model selection
# ---------------------------------------------------------------------------


def test_time_arithmetic():
    m = cm.CostModel({"links": {"ici": {"alpha_us": 2.0,
                                        "gb_per_s": 1.0}},
                      "gamma_gb_per_s": 1.0})
    # 1 GB/s == 1000 bytes/us
    c = cm.OpCost(ici=cm.LinkTerm(3, 5000), gamma_bytes=2000)
    assert m.time_us(c) == pytest.approx(3 * 2.0 + 5.0 + 2.0)


def test_best_algo_crossover_behavior():
    # tiny payload: log-depth butterfly wins; huge payload: ring wins;
    # multi-host huge payload: the two-level lowering wins (the
    # flat-vs-hier sign the --hierarchy-sweep acceptance compares)
    best, _ = cm.best_algo("allreduce", 1 << 10, 8, MODEL)
    assert best == "butterfly"
    best, _ = cm.best_algo("allreduce", 1 << 24, 8, MODEL)
    assert best == "ring"
    best, times = cm.best_algo("allreduce", 1 << 24, 8, MODEL,
                               hosts=2, hier=(2, 4))
    assert best == "hier"
    assert times["hier"] < times["ring"] < times["butterfly"]


def test_stamp_is_hashable_and_param_sensitive():
    a = cm.CostModel().stamp()
    b = cm.CostModel({"links": {"ici": {"alpha_us": 9.0}}}).stamp()
    assert hash(a) != hash(b) or a != b
    assert a == cm.CostModel().stamp()


# ---------------------------------------------------------------------------
# tuning-file parse / accept / reject
# ---------------------------------------------------------------------------

GOOD = {
    "schema": "mpx-cost-model/1",
    "source": "benchmarks/micro.py --cost-calibrate (cpu, 8 devices)",
    "links": {"ici": {"alpha_us": 1.5, "gb_per_s": 42.0},
              "dcn": {"alpha_us": 30.0, "gb_per_s": 9.0}},
    "gamma_gb_per_s": 350.0,
    "compute_gb_per_s": 250.0,
    "dispatch_us": 100.0,
    "measured": {"ring_crossover_bytes": 917504},
}


def test_tuning_file_roundtrip(tmp_path):
    path = tmp_path / "model.json"
    path.write_text(json.dumps(GOOD))
    m = cm.model_from_file(str(path))
    assert m.params["links"]["ici"]["gb_per_s"] == 42.0
    assert m.params["links"]["dcn"]["alpha_us"] == 30.0
    assert m.params["dispatch_us"] == 100.0
    assert m.measured["ring_crossover_bytes"] == 917504
    assert m.source == str(path)
    # partial files keep defaults for what they omit
    m = cm.model_from_dict({"links": {"ici": {"alpha_us": 0.5}}})
    assert m.params["links"]["ici"]["gb_per_s"] == \
        cm.DEFAULT_PARAMS["links"]["ici"]["gb_per_s"]


@pytest.mark.parametrize("payload, match", [
    ([1, 2], "JSON object"),
    ({"schema": "mpx-cost-model/999"}, "schema"),
    ({"links": {"nvlink": {"gb_per_s": 1}}}, "unknown"),
    ({"links": {"ici": {"gb_per_s": 0}}}, "must be > 0"),
    ({"links": {"ici": {"gb_per_s": -3}}}, "must be > 0"),
    ({"links": {"ici": {"alpha_us": "fast"}}}, "number"),
    ({"links": {"ici": {"beta": 1.0}}}, "unknown"),
    ({"links": "fast"}, "object"),
    ({"gamma_gb_per_s": 0}, "positive"),
    ({"measured": {"ring_crossover_bytes": "1MiB"}}, "number"),
])
def test_tuning_rejects(payload, match):
    with pytest.raises(ValueError, match=match):
        cm.validate_model_dict(payload)


def test_load_model_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_COST_MODEL", raising=False)
    assert cm.load_model(None).source is None  # analytic defaults
    path = tmp_path / "m.json"
    path.write_text(json.dumps(GOOD))
    monkeypatch.setenv("MPI4JAX_TPU_COST_MODEL", str(path))
    assert cm.load_model(None).params["links"]["ici"]["gb_per_s"] == 42.0
    meta = cm.measured_meta()
    assert meta["cost_model"] == str(path)
    assert meta["measured_ring_crossover_bytes"] == 917504
    # malformed file: analyze raises loudly, measured_meta warns + {}
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setenv("MPI4JAX_TPU_COST_MODEL", str(bad))
    with pytest.raises(ValueError, match="not valid JSON"):
        cm.load_model(None)
    with pytest.warns(UserWarning, match="ignored"):
        assert cm.measured_meta() == {}


def test_calibrate_shaped_payload_loads_verbatim():
    # the benchmarks/micro.py --cost-calibrate output shape (the traced
    # half drives the real generator in tests/test_micro_bench.py)
    m = cm.model_from_dict(GOOD)
    assert "cost-calibrate" in m.source
    # a FULL --save sweep capture (tuning payload embedded under
    # "cost_model") is accepted whole: the artifact IS a tuning file
    sweep = {"platform": "cpu", "n_devices": 8, "allreduce": [],
             "cost_model": GOOD}
    m = cm.model_from_dict(sweep)
    assert m.params["links"]["ici"]["gb_per_s"] == 42.0
    assert m.measured["ring_crossover_bytes"] == 917504


# ---------------------------------------------------------------------------
# measured crossovers reach the MPX111 / MPX113 texts
# ---------------------------------------------------------------------------


def test_mpx113_cites_measured_crossover():
    checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
    graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
    E, G = graph.CollectiveEvent, graph.CollectiveGraph
    e = E(0, "allreduce", comm_uid=1, comm_size=8, payload_bytes=1 << 21,
          dtype="float32", shape=(1,), algo="ring", hosts=2)
    meta = {"ring_crossover_bytes": 1 << 20}
    (f,) = checkers.run_checkers(G(events=[e], meta=dict(meta)))
    assert f.code == "MPX113" and "measured" not in f.message
    meta.update({"measured_ring_crossover_bytes": 1 << 21,
                 "cost_model": "results/cost.json"})
    (f,) = checkers.run_checkers(G(events=[e], meta=dict(meta)))
    assert f.code == "MPX113"
    assert "measured crossover" in f.message
    assert "results/cost.json" in f.message
    # the measured value is also the firing threshold: below it, clean
    meta["measured_ring_crossover_bytes"] = 1 << 22
    assert not checkers.run_checkers(G(events=[e], meta=dict(meta)))


def test_mpx111_cites_measured_bucket():
    checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
    graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
    E, G = graph.CollectiveEvent, graph.CollectiveGraph
    events = [
        E(i, "allreduce", comm_uid=1, reduction="sum",
          payload_bytes=1024, dtype="float32", shape=(256,))
        for i in range(2)
    ]
    meta = {"fusion": "off", "fusion_bucket_bytes": 4 << 20,
            "measured_fusion_bucket_bytes": 2048,
            "cost_model": "results/cost.json"}
    finds = [f for f in checkers.run_checkers(G(events=events,
                                                meta=dict(meta)))
             if f.code == "MPX111"]
    assert len(finds) == 1
    assert "measured 2048 B bucket" in finds[0].message
    assert "results/cost.json" in finds[0].message
    # the measured bucket gates too: payloads above it no longer bucket
    meta["measured_fusion_bucket_bytes"] = 512
    assert not [f for f in checkers.run_checkers(
        G(events=events, meta=dict(meta))) if f.code == "MPX111"]


# ---------------------------------------------------------------------------
# jaxpr traffic estimate (duck-typed fakes)
# ---------------------------------------------------------------------------


class FakeVar:
    def __init__(self, shape, dtype="float32"):
        self.aval = types.SimpleNamespace(shape=shape,
                                          dtype=np.dtype(dtype))


class FakeEqn:
    def __init__(self, outs, params=None):
        self.outvars = outs
        self.params = params or {}


class FakeJaxpr:
    def __init__(self, eqns):
        self.eqns = eqns


def test_jaxpr_traffic_bytes():
    j = FakeJaxpr([FakeEqn([FakeVar((16, 4))]), FakeEqn([FakeVar((8,))])])
    assert cost.jaxpr_traffic_bytes(j) == 16 * 4 * 4 + 8 * 4
    # a loop body counts ONCE, never x trip count: the event stream
    # records a loop body's collectives once too (the body traces
    # once), so compute and communication must cover the same window —
    # multiplying by length would false-fire MPX131 on every unrolled
    # megastep (compute priced for N steps, comm for 1)
    body = FakeJaxpr([FakeEqn([FakeVar((10,))])])
    loop = FakeJaxpr([FakeEqn([FakeVar((999,))],
                              {"jaxpr": body, "length": 5})])
    assert cost.jaxpr_traffic_bytes(loop) == 40
    # cond counts its widest branch
    b1 = FakeJaxpr([FakeEqn([FakeVar((1,))])])
    b2 = FakeJaxpr([FakeEqn([FakeVar((100,))])])
    swtch = FakeJaxpr([FakeEqn([FakeVar((1,))], {"branches": (b1, b2)})])
    assert cost.jaxpr_traffic_bytes(swtch) == 400
    assert cost.jaxpr_traffic_bytes(None) == 0


def test_topology_helpers():
    host_of_rank = (0, 0, 0, 0, 1, 1, 1, 1)
    assert topology.span_hosts(host_of_rank, [0, 1, 2]) == 1
    assert topology.span_hosts(host_of_rank, [0, 4]) == 2
    assert topology.link_class(host_of_rank, 0, 1) == "ici"
    assert topology.link_class(host_of_rank, 0, 4) == "dcn"
    assert topology.link_class(None, 0, 4) == "ici"


# ---------------------------------------------------------------------------
# scripted schedules -> critical-path simulation
# ---------------------------------------------------------------------------


def coll(rank, pos, op="allreduce", seq=0, parts=(0, 1, 2, 3),
         nbytes=1 << 20, algo="ring", **kw):
    return S(rank=rank, pos=pos, kind="coll", op=op, comm_key=0, seq=seq,
             participants=tuple(parts), payload_bytes=nbytes, algo=algo,
             **kw)


def ladder_schedules(ranks=4, nbytes=1 << 16):
    schedules = {r: [] for r in range(ranks)}
    for s in range(1, ranks):
        schedules[s - 1].append(
            S(rank=s - 1, pos=len(schedules[s - 1]), kind="send", op="send",
              comm_key=0, src=s - 1, dst=s, tag=s, payload_bytes=nbytes))
        schedules[s].append(
            S(rank=s, pos=len(schedules[s]), kind="recv", op="recv",
              comm_key=0, src=s - 1, dst=s, tag=s, payload_bytes=nbytes))
    return schedules


def run(schedules, **kw):
    matched = matcher.match_schedules(schedules)
    assert not matched.findings, matched.findings
    return cost.run_cost_pass(matched, model=kw.pop("model", MODEL), **kw)


def test_collective_sequence_times_and_breakdown():
    # 4 ranks, 2 ring allreduces back to back: the path is exactly
    # 2 x the instance time, every byte on the ICI class
    schedules = {r: [coll(r, 0, seq=0), coll(r, 1, seq=1)]
                 for r in range(4)}
    rep, findings = run(schedules)
    assert rep is not None
    one = MODEL.time_us(cm.collective_cost("allreduce", "ring",
                                           1 << 20, 4))
    assert rep.path_us == pytest.approx(2 * one)
    assert rep.total_us == pytest.approx(2 * one + MODEL.dispatch_us)
    assert rep.per_op["allreduce"]["count"] == 2
    assert rep.per_link["dcn"]["bytes"] == 0
    assert rep.per_link["ici"]["bytes"] > 0
    assert rep.amortization["megastep_per_step_host_us"]["8"] == \
        pytest.approx(MODEL.dispatch_us / 8)
    assert [n["op"] for n in rep.critical_path] == ["allreduce"] * 2
    json.dumps(rep.to_json())  # CI-consumable
    assert "predicted step time" in rep.render()


def test_straggler_defines_collective_completion():
    # the last-arriving member gates the collective: rank 3's slow
    # compute (fat fake jaxpr) pushes every member's completion
    schedules = {r: [coll(r, 0)] for r in range(4)}
    closed = {3: FakeJaxpr([FakeEqn([FakeVar((1 << 22,))])])}
    rep, _ = run(schedules, closed=closed)
    slow = MODEL.compute_us(1 << 24) / 2  # one of two gaps
    one = MODEL.time_us(cm.collective_cost("allreduce", "ring",
                                           1 << 20, 4))
    # missing ranks reuse the first available estimate, so every rank
    # carries the same gap here — completion includes one gap + op
    assert rep.path_us == pytest.approx(2 * slow + one)


def test_deadlock_yields_no_cost_report():
    # head-to-head recv-first exchange: progress residue -> no timing
    schedules = {
        0: [S(rank=0, pos=0, kind="recv", op="recv", comm_key=0, src=1,
              dst=0, tag=0),
            S(rank=0, pos=1, kind="send", op="send", comm_key=0, src=0,
              dst=1, tag=1)],
        1: [S(rank=1, pos=0, kind="recv", op="recv", comm_key=0, src=0,
              dst=1, tag=1),
            S(rank=1, pos=1, kind="send", op="send", comm_key=0, src=1,
              dst=0, tag=0)],
    }
    matched = matcher.match_schedules(schedules)
    rep, findings = cost.run_cost_pass(matched, model=MODEL)
    assert rep is None and findings == []


def test_start_wait_overlap_is_visible():
    # start ... wait on 2 ranks: the wait completes at start-issue +
    # op time; with no compute in the gap the whole op time is exposed
    def sw(r):
        return [
            S(rank=r, pos=0, kind="start", op="allreduce_start",
              comm_key=0, seq=0, participants=(0, 1),
              payload_bytes=1 << 20, algo="butterfly", span=7),
            S(rank=r, pos=1, kind="wait", op="allreduce_wait", comm_key=0,
              seq=0, participants=(0, 1), payload_bytes=1 << 20,
              algo="butterfly", span=7),
        ]
    rep, _ = run({0: sw(0), 1: sw(1)})
    one = MODEL.time_us(cm.collective_cost("allreduce", "butterfly",
                                           1 << 20, 2))
    assert rep.path_us == pytest.approx(one)
    # async spans account under the BASE op name: one collective type,
    # one per-op row, blocking or split
    assert rep.per_op["allreduce"]["count"] == 1
    assert "allreduce_wait" not in rep.per_op


# ---------------------------------------------------------------------------
# the critic: MPX131-135 positive/negative
# ---------------------------------------------------------------------------


def test_mpx131_overlap_opportunity():
    schedules = {r: [coll(r, 0)] for r in range(4)}
    # big adjacent compute: the gap can hide most of the collective
    closed = {r: FakeJaxpr([FakeEqn([FakeVar((1 << 22,))])])
              for r in range(4)}
    rep, findings = run(schedules, closed=closed)
    f = [x for x in findings if x.code == "MPX131"]
    assert len(f) == 1
    assert "hide" in f[0].message and "us" in f[0].message
    # negative: no compute to hide behind
    _, findings = run(schedules)
    assert not [x for x in findings if x.code == "MPX131"]


def test_mpx132_fusion_savings_quantified():
    schedules = {
        r: [coll(r, 0, seq=0, nbytes=1 << 16, algo="butterfly",
                 reduction="sum"),
            coll(r, 1, seq=1, nbytes=1 << 16, algo="butterfly",
                 reduction="sum")]
        for r in range(4)
    }
    meta = {"fusion": "off", "fusion_bucket_bytes": 4 << 20}
    rep, findings = run(schedules, meta=meta)
    f = [x for x in findings if x.code == "MPX132"]
    assert len(f) == 1
    assert "us saved per step" in f[0].message
    assert rep.amortization["fusion_savings_us"] > 0
    # negative: fusion already on
    _, findings = run(schedules, meta={"fusion": "auto"})
    assert not [x for x in findings if x.code == "MPX132"]
    # negative: payloads above the measured bucket cap never bucket
    meta = {"fusion": "off", "fusion_bucket_bytes": 4 << 20,
            "measured_fusion_bucket_bytes": 1024}
    _, findings = run(schedules, meta=meta)
    assert not [x for x in findings if x.code == "MPX132"]


def test_mpx133_algorithm_mispick():
    # 16 MiB on the butterfly: the model predicts the ring, loudly
    schedules = {r: [coll(r, 0, nbytes=1 << 24, algo="butterfly")]
                 for r in range(4)}
    _, findings = run(schedules)
    f = [x for x in findings if x.code == "MPX133"]
    assert len(f) == 1
    assert "'ring'" in f[0].message and "us/step faster" in f[0].message
    assert "MPI4JAX_TPU_COLLECTIVE_ALGO=ring" in f[0].suggestion
    # negative: the chosen algo IS the model's pick
    schedules = {r: [coll(r, 0, nbytes=1 << 24, algo="ring")]
                 for r in range(4)}
    _, findings = run(schedules)
    assert not [x for x in findings if x.code == "MPX133"]


def test_mpx134_structural_imbalance():
    schedules = {
        r: [coll(r, 0, nbytes=(1 << 20) * (2 if r == 3 else 1))]
        for r in range(4)
    }
    _, findings = run(schedules)
    f = [x for x in findings if x.code == "MPX134"]
    assert len(f) == 1
    assert f[0].rank == 3 and "straggler by construction" in f[0].message
    # negative: uniform payloads
    _, findings = run({r: [coll(r, 0)] for r in range(4)})
    assert not [x for x in findings if x.code == "MPX134"]


def test_mpx135_serialized_chain_positive_negative():
    _, findings = run(ladder_schedules(ranks=4))
    f = [x for x in findings if x.code == "MPX135"]
    assert len(f) == 1
    assert "microbatch" in f[0].suggestion
    assert "critical path" in f[0].message
    # negative: a 2-rank ping-pong never spans enough ranks
    schedules = {
        0: [S(rank=0, pos=0, kind="send", op="send", comm_key=0, src=0,
              dst=1, tag=0, payload_bytes=64),
            S(rank=0, pos=1, kind="recv", op="recv", comm_key=0, src=1,
              dst=0, tag=1, payload_bytes=64)],
        1: [S(rank=1, pos=0, kind="recv", op="recv", comm_key=0, src=0,
              dst=1, tag=0, payload_bytes=64),
            S(rank=1, pos=1, kind="send", op="send", comm_key=0, src=1,
              dst=0, tag=1, payload_bytes=64)],
    }
    _, findings = run(schedules)
    assert not [x for x in findings if x.code == "MPX135"]


def test_moe_fixture_mpx133_and_mpx131():
    # the seeded naive-MoE shape: dispatch alltoall -> expert compute ->
    # combine alltoall, both exchanges run FLAT on a 2x4 multi-host comm
    # at a payload where the model prefers the two-level split, with
    # enough adjacent compute to hide most of the wire.  The critic must
    # flag BOTH levers this PR builds: the algorithm mispick (MPX133 ->
    # hier) and the overlap opportunity (MPX131 -> alltoall_start).
    ranks = 8
    schedules = {
        r: [coll(r, 0, op="alltoall", seq=0, parts=tuple(range(ranks)),
                 nbytes=1 << 20, algo="native", hosts=2),
            coll(r, 1, op="alltoall", seq=1, parts=tuple(range(ranks)),
                 nbytes=1 << 20, algo="native", hosts=2)]
        for r in range(ranks)
    }
    closed = {r: FakeJaxpr([FakeEqn([FakeVar((1 << 25,))])])
              for r in range(ranks)}
    rep, findings = run(schedules, closed=closed)
    assert rep is not None
    mispicks = [x for x in findings if x.code == "MPX133"]
    assert len(mispicks) == 1  # deduped per (op, comm, bytes, pick)
    assert "'hier'" in mispicks[0].message
    assert "alltoall" in mispicks[0].message
    overlaps = [x for x in findings if x.code == "MPX131"]
    assert len(overlaps) == 1
    assert "alltoall_start/alltoall_wait" in overlaps[0].suggestion
    # negative: the hier pick with no idle compute is clean on both
    schedules = {
        r: [coll(r, 0, op="alltoall", seq=0, parts=tuple(range(ranks)),
                 nbytes=1 << 20, algo="hier", hosts=2, hier=(2, 4))]
        for r in range(ranks)
    }
    _, findings = run(schedules)
    assert not [x for x in findings if x.code in ("MPX131", "MPX133")]


def test_wildcard_recv_skips_sends_consumed_by_specific_recvs():
    # rank 2 receives from rank 0 BY SOURCE, then from anyone: the
    # wildcard must pair with rank 1's still-unconsumed send (a DCN
    # hop here), exactly as the untimed simulation pairs them — not
    # with rank 0's already-consumed one (regression: the timed pool
    # must drain on specific recvs too)
    schedules = {
        0: [S(rank=0, pos=0, kind="send", op="send", comm_key=0, src=0,
              dst=2, tag=0, payload_bytes=1 << 16)],
        1: [S(rank=1, pos=0, kind="send", op="send", comm_key=0, src=1,
              dst=2, tag=0, payload_bytes=1 << 16)],
        2: [S(rank=2, pos=0, kind="recv", op="recv", comm_key=0, src=0,
              dst=2, tag=0, payload_bytes=1 << 16),
            S(rank=2, pos=1, kind="recv", op="recv", comm_key=0, src=None,
              dst=2, tag=0, payload_bytes=1 << 16)],
    }
    host_of_rank = (0, 1, 0)  # rank 1 lives across the DCN
    rep, _ = run(schedules, host_of_rank=host_of_rank)
    assert rep.per_link["ici"]["rounds"] == 1  # 0 -> 2, by source
    assert rep.per_link["dcn"]["rounds"] == 1  # 1 -> 2, wildcard


def test_mpx132_never_fires_on_eager_ops():
    # an eager op never enters the fusion queue (MPX111's rule): the
    # quantified twin must mirror the exclusion
    schedules = {
        r: [coll(r, 0, seq=0, nbytes=1 << 16, algo="butterfly",
                 reduction="sum", eager=True),
            coll(r, 1, seq=1, nbytes=1 << 16, algo="butterfly",
                 reduction="sum", eager=True)]
        for r in range(4)
    }
    _, findings = run(schedules,
                      meta={"fusion": "off",
                            "fusion_bucket_bytes": 4 << 20})
    assert not [x for x in findings if x.code == "MPX132"]


def test_multi_host_ladder_prices_on_dcn():
    host_of_rank = (0, 0, 1, 1)
    rep, _ = run(ladder_schedules(ranks=4), host_of_rank=host_of_rank)
    # hops 0->1 and 2->3 are ICI, 1->2 crosses hosts
    assert rep.per_link["dcn"]["rounds"] == 1
    assert rep.per_link["ici"]["rounds"] == 2


def test_cost_codes_are_advisory():
    report = sys.modules[f"{_ISO_NAME}.analysis.report"]
    for code in cost.COST_CODES:
        assert report.CODES[code].severity == report.ADVISORY
