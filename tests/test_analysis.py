"""Trace-time verifier: the traced integration half (docs/analysis.md).

Every hazard program docs/sharp_bits.md can express today — unmatched
send, bare-int dest, traced root, out-of-range root, dropped token,
signature mismatch, cond divergence, crossover proximity, ambiguous
FIFO — reproduced as a fixture and driven through BOTH front-ends:

- ``mpx.analyze`` (abstract re-trace, findings as a Report);
- the ``MPI4JAX_TPU_ANALYZE=error`` dispatch path (trace-time raise).

Plus the zero-cost contract (HLO byte-identical across modes) and the
``clear_caches`` retrace test mirroring the PR-2 algo-toggle test.
The pure-Python checker half lives in tests/test_analysis_pure.py.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import ranks_arange, world


@pytest.fixture(autouse=True)
def _reset_analysis(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE", raising=False)
    yield
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


def codes(report):
    return [f.code for f in report.findings]


# ---------------------------------------------------------------------------
# hazard fixtures (one per sharp bit), through mpx.analyze
# ---------------------------------------------------------------------------


def fx_unmatched_send(x):
    mpx.send(x, dest=mpx.shift(1))
    return x


def fx_recv_without_send(x):
    y, _ = mpx.recv(x)
    return y


def fx_bare_int_dest(x):
    y, _ = mpx.sendrecv(x, x, dest=1)
    return y


def fx_traced_root(x):
    comm = mpx.get_default_comm()
    res, _ = mpx.bcast(x, comm.Get_rank())  # traced value as structure
    return res


def fx_root_out_of_range(x):
    res, _ = mpx.bcast(x, 17)
    return res


def fx_signature_mismatch(x):
    y, _ = mpx.sendrecv(x, x.astype(jnp.int32), dest=mpx.shift(1))
    return y


def fx_dropped_token(x):
    t = mpx.create_token()
    a, t1 = mpx.allreduce(x, token=t)
    b, t2 = mpx.allreduce(x * 2, token=t)  # forked from t: t1 is dropped
    return a + b


def fx_ambiguous_fifo(x):
    t1 = mpx.send(x, dest=mpx.shift(1))
    t2 = mpx.send(x * 2, dest=mpx.shift(1), token=t1)
    a, _ = mpx.recv(x, token=t2)
    b, _ = mpx.recv(x, token=t2)
    return a + b


def fx_clean(x):
    t = mpx.create_token()
    a, t = mpx.allreduce(x, token=t)
    b, t = mpx.sendrecv(a, a, dest=mpx.shift(1), token=t)
    return b


HAZARDS = [
    (fx_unmatched_send, "MPX101", "unmatched send"),
    (fx_recv_without_send, "MPX102", "no matching send"),
    (fx_bare_int_dest, "MPX103", "bare int"),
    (fx_traced_root, "MPX104", "tracer"),
    (fx_root_out_of_range, "MPX105", "out of range"),
    (fx_signature_mismatch, "MPX106", "dtypes"),
    (fx_dropped_token, "MPX107", "older token"),
    (fx_ambiguous_fifo, "MPX110", "FIFO"),
]


@pytest.mark.parametrize("fn,code,fragment", HAZARDS,
                         ids=[h[1] for h in HAZARDS])
def test_hazard_fixture_flagged_by_analyze(fn, code, fragment):
    report = mpx.analyze(fn, ranks_arange((4,)))
    # exactly one finding per defect: a trace-aborting hazard must not be
    # double-reported by the graph checkers replaying the same events
    assert codes(report).count(code) == 1, report.render()
    finding = next(f for f in report.findings if f.code == code)
    assert fragment in finding.message
    rendered = report.render()
    assert code in rendered


@pytest.mark.parametrize("fn,code,fragment", HAZARDS,
                         ids=[h[1] for h in HAZARDS])
def test_hazard_fixture_flagged_by_dispatch_env_mode(fn, code, fragment):
    """The same fixtures through the ambient MPI4JAX_TPU_ANALYZE=error
    path: structural hazards raise their tagged exception at trace time;
    stream hazards raise AnalysisError when the region's trace completes."""
    mpx.set_analyze_mode("error")
    x = ranks_arange((4,))
    with pytest.raises(Exception, match=code) as ei:
        np.asarray(mpx.run(fn, x))
    exc = ei.value
    assert getattr(exc, "mpx_code", None) == code or isinstance(
        exc, mpx.AnalysisError)


def test_clean_program_analyzes_clean():
    report = mpx.analyze(fx_clean, ranks_arange((4,)))
    assert report.ok, report.render()
    assert len(report.events) == 2  # allreduce + sendrecv
    assert "clean" in report.render()


def test_clean_program_runs_under_error_mode():
    _, size = world()
    mpx.set_analyze_mode("error")
    out = np.asarray(mpx.run(fx_clean, ranks_arange((4,))))
    assert out.shape == (size, 4)


def test_warn_mode_warns_instead_of_raising():
    mpx.set_analyze_mode("warn")
    with pytest.warns(UserWarning, match="MPX107"):
        out = mpx.run(fx_dropped_token, ranks_arange((4,)))
    assert np.asarray(out).shape == (world()[1], 4)


# ---------------------------------------------------------------------------
# MPX111: adjacent fusable collectives not fused (fusion advisory)
# ---------------------------------------------------------------------------


def _adjacent_allreduces(x):
    a, _ = mpx.allreduce(x, op=mpx.SUM)
    b, _ = mpx.allreduce(x * 2, op=mpx.SUM)
    return mpx.varying(a * 1.0), mpx.varying(b * 1.0)


def test_mpx111_adjacent_unfused_advisory():
    report = mpx.analyze(_adjacent_allreduces, ranks_arange((4,)))
    assert codes(report) == ["MPX111"], report.render()
    (f,) = report.findings
    assert f.severity == "advisory"
    assert "MPI4JAX_TPU_FUSION=auto" in f.suggestion

    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError, match="MPX111"):
        mpx.run(_adjacent_allreduces, ranks_arange((4,)))


def test_mpx111_silent_when_fusion_on():
    mpx.set_fusion_mode("auto")
    try:
        report = mpx.analyze(_adjacent_allreduces, ranks_arange((4,)))
        assert report.ok, report.render()
        # the stream records ONE fused collective carrying both members
        fused = [e for e in report.events if e.op == "allreduce"]
        assert len(fused) == 1
        assert fused[0].fused_members == 2
    finally:
        mpx.set_fusion_mode(None)


def test_mpx111_silent_for_different_reductions():
    def f(x):
        a, _ = mpx.allreduce(x, op=mpx.SUM)
        b, _ = mpx.allreduce(x, op=mpx.MAX)
        return mpx.varying(a * 1.0), mpx.varying(b * 1.0)

    report = mpx.analyze(f, ranks_arange((4,)))
    assert report.ok, report.render()


# ---------------------------------------------------------------------------
# MPX112: async start/wait pairing
# ---------------------------------------------------------------------------


def _start_without_wait(x):
    h, _ = mpx.allreduce_start(x, op=mpx.SUM)
    return mpx.varying(x * 1.0)


def _paired_start_wait(x):
    h, _ = mpx.allreduce_start(x, op=mpx.SUM)
    y = x * 3.0  # independent compute in the gap
    s, _ = mpx.allreduce_wait(h)
    return mpx.varying(s + 0 * y)


def test_mpx112_unwaited_start_flagged():
    report = mpx.analyze(_start_without_wait, ranks_arange((4,)))
    assert "MPX112" in codes(report), report.render()
    f = next(f for f in report.findings if f.code == "MPX112")
    assert "never waited" in f.message

    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError, match="MPX112"):
        mpx.run(_start_without_wait, ranks_arange((4,)))


def test_mpx112_paired_start_wait_clean():
    report = mpx.analyze(_paired_start_wait, ranks_arange((4,)))
    assert report.ok, report.render()
    ops = [e.op for e in report.events]
    assert ops == ["allreduce_start", "allreduce_wait"]
    start, wait = report.events
    assert start.span == wait.span is not None

    mpx.set_analyze_mode("error")
    out = np.asarray(mpx.run(_paired_start_wait, ranks_arange((4,))))
    assert out.shape == (world()[1], 4)


# ---------------------------------------------------------------------------
# MPX108: cond divergence (jaxpr walker, analyze-only)
# ---------------------------------------------------------------------------


def test_mpx108_cond_divergence_flagged():
    def f(x):
        def talk(v):
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            return mpx.varying(s)

        def quiet(v):
            return v

        return jax.lax.cond(x.sum() > 0, talk, quiet, x)

    report = mpx.analyze(f, ranks_arange((4,)))
    assert "MPX108" in codes(report), report.render()
    finding = next(f for f in report.findings if f.code == "MPX108")
    assert "disagree" in finding.message


def test_mpx108_negative_both_branches_communicate():
    def f(x):
        def a(v):
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            return mpx.varying(s)

        def b(v):
            s, _ = mpx.allreduce(v, op=mpx.MAX)
            return mpx.varying(s)

        return jax.lax.cond(x.sum() > 0, a, b, x)

    report = mpx.analyze(f, ranks_arange((4,)))
    assert "MPX108" not in codes(report), report.render()


# ---------------------------------------------------------------------------
# MPX109: crossover proximity (payload-aware selector advisory)
# ---------------------------------------------------------------------------


def _prod_reduce(x):
    # PROD has no native HLO collective, so the payload-aware selector
    # (ops/_algos.py) is consulted and the event carries the chosen algo
    res, _ = mpx.allreduce(x, op=mpx.PROD)
    return res


def test_mpx109_near_crossover_advisory(monkeypatch):
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "4096")
    x = ranks_arange((1024,))  # 4096 B/rank: exactly at the crossover
    report = mpx.analyze(_prod_reduce, x)
    assert codes(report) == ["MPX109"], report.render()
    (f,) = report.findings
    assert f.severity == "advisory"
    assert "within 2x" in f.message

    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError, match="MPX109"):
        mpx.run(_prod_reduce, x)


def test_mpx109_negative_far_from_crossover(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", str(1 << 24))
    report = mpx.analyze(_prod_reduce, ranks_arange((8,)))
    assert report.ok, report.render()
    (evt,) = report.events
    assert evt.algo == "butterfly"  # selector consulted, advisory silent


def test_mpx109_forced_algo_is_deterministic_hence_clean(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "4096")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    report = mpx.analyze(_prod_reduce, ranks_arange((1024,)))
    assert report.ok, report.render()


# ---------------------------------------------------------------------------
# MPX113 — flat algorithm on a multi-host comm (docs/topology.md)
# ---------------------------------------------------------------------------


def test_mpx113_flat_on_multihost_advisory(monkeypatch):
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1024")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    x = ranks_arange((1024,))  # 4096 B/rank, above the crossover
    report = mpx.analyze(_prod_reduce, x)
    assert codes(report) == ["MPX113"], report.render()
    (f,) = report.findings
    assert f.severity == "advisory"
    assert "2 hosts" in f.message and "'ring'" in f.message
    assert "hier" in f.suggestion

    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError, match="MPX113"):
        mpx.run(_prod_reduce, x)


def test_mpx113_negative_auto_picks_hier(monkeypatch):
    # same topology and payload, but auto: the two-level lowering runs
    # and there is nothing to advise about
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1024")
    report = mpx.analyze(_prod_reduce, ranks_arange((1024,)))
    assert report.ok, report.render()
    (evt,) = report.events
    assert evt.algo == "hier" and evt.hosts == 2


def test_mpx113_negative_single_host_and_small_payload(monkeypatch):
    _, size = world()
    # no topology: a forced ring is as good as it gets — clean
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1024")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    report = mpx.analyze(_prod_reduce, ranks_arange((1024,)))
    assert report.ok, report.render()
    # multi-host but below the crossover: the flat butterfly is right
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "butterfly")
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", str(1 << 24))
    report = mpx.analyze(_prod_reduce, ranks_arange((1024,)))
    assert report.ok, report.render()
    # non-uniform host partition: flat is the ONLY option — clean
    monkeypatch.setenv(
        "MPI4JAX_TPU_TOPOLOGY", f"{size - 3},3" if size > 3 else "1,1")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1024")
    report = mpx.analyze(_prod_reduce, ranks_arange((1024,)))
    assert report.ok, report.render()


# ---------------------------------------------------------------------------
# the event stream (graph extraction)
# ---------------------------------------------------------------------------


def test_event_stream_records_structure():
    _, size = world()

    def f(x):
        a, t = mpx.bcast(x, 2)
        b, t = mpx.sendrecv(a, a, dest=mpx.shift(1), sendtag=7, token=t)
        c, t = mpx.allreduce(b, op=mpx.SUM, token=t)
        return c

    report = mpx.analyze(f, ranks_arange((4,)))
    assert report.ok, report.render()
    bcast_e, sr_e, ar_e = report.events
    assert (bcast_e.op, bcast_e.root) == ("bcast", 2)
    assert bcast_e.comm_size == size and not bcast_e.split
    assert sr_e.op == "sendrecv" and sr_e.tag == 7
    assert sr_e.pairs == tuple(((r, (r + 1) % size) for r in range(size)))
    assert ar_e.reduction == "sum"
    assert ar_e.algo == "native"
    assert ar_e.payload_bytes == 4 * 4
    # the token chain is linear: each op consumes the previous token
    assert sr_e.token_in == bcast_e.token_out
    assert ar_e.token_in == sr_e.token_out


def test_analyze_spmd_decorated_function():
    @mpx.spmd
    def step(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    # the decorated wrapper is analyzed via its underlying per-rank body
    # (jit caches cannot hide ops from the verifier) — even AFTER a real
    # call populated the jit caches
    x = ranks_arange((4,))
    np.asarray(step(x))
    report = mpx.analyze(step, x)
    assert report.ok
    assert [e.op for e in report.events] == ["allreduce"]


def test_analyze_eager_style_function():
    x = ranks_arange((4,))

    def eager(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    report = mpx.analyze(eager, x, wrap=False)
    assert report.ok
    assert [e.op for e in report.events] == ["allreduce"]
    assert report.events[0].eager


def test_eager_dispatch_env_mode(monkeypatch):
    """The ambient mode covers eager one-op programs too, and flipping the
    mode retraces (the mode is folded into the eager cache key)."""
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "4096")
    x = ranks_arange((1024,))
    # populate the off-mode cache first: the error-mode flip must not be
    # hidden by the cached program
    np.asarray(mpx.allreduce(x, op=mpx.PROD)[0])
    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError, match="MPX109"):
        mpx.allreduce(x, op=mpx.PROD)


# ---------------------------------------------------------------------------
# zero-cost contract + caches
# ---------------------------------------------------------------------------


def test_hlo_byte_identical_across_modes():
    """The acceptance-criteria pin: recording is host-side bookkeeping, so
    the lowered HLO with the verifier off is byte-identical to warn and
    error modes (off-mode lowering == seed lowering by construction: the
    traced program contains no analysis code in any mode)."""
    x = ranks_arange((16,))

    def lowered():
        @mpx.spmd
        def f(xl):
            a, t = mpx.allreduce(xl, op=mpx.SUM)
            b, t = mpx.sendrecv(a, a, dest=mpx.shift(1), token=t)
            return b

        return jax.jit(f).lower(x).as_text()

    mpx.set_analyze_mode(None)
    off = lowered()
    mpx.set_analyze_mode("warn")
    assert lowered() == off
    mpx.set_analyze_mode("error")
    assert lowered() == off


def test_analyze_memo_and_clear_caches(monkeypatch):
    """Mirrors the PR-2 algo-toggle retrace test: the analyze memo must be
    keyed on the algorithm config (a crossover flip changes the verdict
    without clear_caches) and mpx.clear_caches() must drop the memo."""
    x = ranks_arange((1024,))
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", str(1 << 24))
    r1 = mpx.analyze(_prod_reduce, x)
    assert r1.ok
    assert mpx.analyze(_prod_reduce, x) is r1  # memoized
    # flipping the crossover must re-analyze (config is in the memo key),
    # and the same payload now sits at the crossover: advisory fires
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "4096")
    r2 = mpx.analyze(_prod_reduce, x)
    assert r2 is not r1
    assert codes(r2) == ["MPX109"]
    # clear_caches drops the memo: same config, fresh report object
    r3 = mpx.analyze(_prod_reduce, x)
    assert r3 is r2
    mpx.clear_caches()
    r4 = mpx.analyze(_prod_reduce, x)
    assert r4 is not r2 and codes(r4) == ["MPX109"]


def test_off_mode_records_nothing():
    """With the verifier off (default), regions carry no recorder and no
    events — the zero-overhead contract for the hot path."""
    from mpi4jax_tpu.parallel.region import RegionContext

    assert RegionContext(None).analysis_recorder is None
    mpx.set_analyze_mode(None)
    # a hazard program traces fine with the verifier off (seed behavior:
    # MPX107/109/110 were never hard errors)
    with warnings.catch_warnings():
        # any verifier warning would fail the test (jax's own unrelated
        # warnings are left alone)
        warnings.filterwarnings("error", message=".*MPI4JAX_TPU_ANALYZE.*")
        out = mpx.run(fx_dropped_token, ranks_arange((4,)))
    assert np.asarray(out).shape == (world()[1], 4)
