"""Traced half of the AOT pinning + persistent compile cache suite
(docs/aot.md): everything that needs real traces on the 8-device
virtual CPU mesh.

- pinned == jit bit-identity for the token, notoken, and eager
  (wrap=False) paths — a pin is the SAME program, only the call path
  changes;
- buffer donation through ``donate_argnums``;
- HLO and program-cache-key byte-identity with the cache dir unset (the
  AOT layer must be invisible until asked for);
- the persistent tier: in-process re-pin served from disk, a
  second-process cold start served from disk (subprocess drill, slow),
  and the spmd program-cache consult on miss;
- staleness: config-stamp and elastic-epoch changes raise
  ``StaleProgramError`` (MPX129) — through direct calls, ``mpx.analyze``
  and the ambient error mode — and ``repin()``/``mpx.elastic.run``
  re-enter the new world (the shrink drill keeps its pinned hot path);
- MPX128 (unpinned hot loop) positive/negative through ``mpx.analyze``
  and env=error, including the being-pinned gate.

The pure half (keys, disk cache, stale state machine, MPX128 checker on
hand-built graphs) runs under any JAX in tests/test_aot_pure.py via the
isolated loader.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.aot import serialization
from mpi4jax_tpu.ops._base import dynamic_cache_token
from mpi4jax_tpu.resilience import elastic as el

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_aot_state():
    """Every test starts at epoch 0 with cold caches, no telemetry/
    analyze override, and no cache dir unless it sets one."""
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    yield
    mpx.set_telemetry_mode(None)
    mpx.set_analyze_mode(None)
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    from mpi4jax_tpu.parallel import region as _region

    _region._default_comm = None


def _world_comm():
    mesh = mpx.make_world_mesh()
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def _reduce_step(v):
    s, _ = mpx.allreduce(v, op=mpx.SUM)
    return mpx.varying(s * 0.5)


# ---------------------------------------------------------------------------
# pinned == jit bit-identity
# ---------------------------------------------------------------------------


def test_pinned_matches_spmd_token_path():
    comm = _world_comm()
    k = comm.Get_size()

    def step(v):
        tok = mpx.create_token()
        s, tok = mpx.allreduce(v, op=mpx.SUM, token=tok)
        b, tok = mpx.bcast(mpx.varying(s), 0, token=tok)
        return mpx.varying(b + v)

    x = jnp.arange(k * 6, dtype=jnp.float32).reshape(k, 6)
    want = np.asarray(mpx.spmd(step, comm=comm)(x))
    pinned = mpx.compile(step, x, comm=comm)
    got = np.asarray(pinned(x))
    np.testing.assert_array_equal(want, got)
    assert mpx.cache_stats()["aot"]["pins"] == 1
    assert mpx.cache_stats()["aot"]["calls"] == 1


def test_pinned_matches_spmd_notoken_path(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_PREFER_NOTOKEN", "1")
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 4), 2.0, jnp.float32)
    want = np.asarray(mpx.spmd(_reduce_step, comm=comm)(x))
    pinned = mpx.compile(_reduce_step, x, comm=comm)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_pinned_matches_eager_wrap_false():
    comm = _world_comm()
    k = comm.Get_size()

    def eager_fn(v):
        # global arrays, ops outside any region (the eager convention)
        s, _ = mpx.allreduce(v, op=mpx.SUM, comm=comm)
        return s + 1.0

    x = jnp.arange(k * 3, dtype=jnp.float32).reshape(k, 3)
    want = np.asarray(eager_fn(x))
    pinned = mpx.compile(eager_fn, x, comm=comm, wrap=False)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_pinned_spmd_decorated_with_static_argnums():
    comm = _world_comm()
    k = comm.Get_size()

    @mpx.spmd(comm=comm, static_argnums=(1,))
    def step(v, n):
        out = v
        for _ in range(n):
            out = mpx.varying(mpx.allreduce(out, op=mpx.SUM)[0] / k)
        return out

    x = jnp.full((k, 4), 3.0, jnp.float32)
    want = np.asarray(step(x, 2))
    # breadcrumbs adopted: comm, static_argnums — the static folds at
    # pin time and the pinned call takes only the dynamic args
    pinned = mpx.compile(step, x, 2)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_donation_is_plumbed():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 8), jnp.float32)
    pinned = mpx.compile(_reduce_step, x, comm=comm, donate_argnums=(0,))
    assert pinned.donate_argnums == (0,)
    out = np.asarray(pinned(jnp.ones((k, 8), jnp.float32)))
    np.testing.assert_array_equal(out, np.full((k, 8), k * 0.5, np.float32))
    # donating a static is a contract error
    with pytest.raises(ValueError, match="donate static"):
        mpx.compile(lambda v, n: v * n, x, 2, comm=comm,
                    static_argnums=(1,), donate_argnums=(1,))


# ---------------------------------------------------------------------------
# invisibility with the cache dir unset
# ---------------------------------------------------------------------------


def test_hlo_and_cache_keys_unchanged_by_aot(monkeypatch, tmp_path):
    """The PR-9 identity: pinning activity and the cache-dir flag must
    not move the dynamic cache token (both program-cache keys) nor the
    lowered HLO of the existing paths."""
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)

    # lower the SAME body construction both paths share
    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.parallel.region import make_region_body

    def lower_text():
        body = make_region_body(_reduce_step, comm, (), (), (), 1,
                                squeeze_in=True, squeeze_out=True)
        sm = jax.jit(jax.shard_map(
            body, mesh=comm.mesh, in_specs=P(comm.axes[0]),
            out_specs=P(comm.axes[0])))
        return sm.lower(x).as_text()

    tok0 = dynamic_cache_token()
    base = lower_text()

    pinned = mpx.compile(_reduce_step, x, comm=comm)
    pinned(x)
    assert lower_text() == base

    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    # the env stamp moved (new raw fingerprint) so the token object is
    # rebuilt — but its VALUE must be identical: the cache-dir flag is
    # not a trace-shaping knob and must not enter program-cache keys
    assert dynamic_cache_token() == tok0
    assert lower_text() == base


# ---------------------------------------------------------------------------
# the persistent tier
# ---------------------------------------------------------------------------

needs_serialization = pytest.mark.skipif(
    not serialization.supported(),
    reason="this jax cannot serialize compiled executables",
)


@needs_serialization
def test_repin_served_from_disk(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 16), 1.5, jnp.float32)

    first = mpx.compile(_reduce_step, x, comm=comm)
    assert not first.from_disk
    want = np.asarray(first(x))
    stats = mpx.cache_stats()
    assert stats["disk_cache"]["writes"] == 1
    assert stats["aot"]["compiles"] == 1

    mpx.clear_caches()  # zero the counters; artifacts stay on disk
    second = mpx.compile(_reduce_step, x, comm=comm)
    assert second.from_disk, "identical program did not load from disk"
    np.testing.assert_array_equal(want, np.asarray(second(x)))
    stats = mpx.cache_stats()
    assert stats["disk_cache"]["hits"] == 1
    assert stats["disk_cache"]["misses"] == 0, "re-lowered on a warm cache"
    assert stats["aot"]["compiles"] == 0
    assert stats["aot"]["disk_loads"] == 1


@needs_serialization
def test_spmd_program_cache_consults_disk_on_miss(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 8), 2.0, jnp.float32)

    want = np.asarray(mpx.spmd(_reduce_step, comm=comm)(x))
    assert mpx.cache_stats()["disk_cache"]["writes"] >= 1
    mpx.clear_caches()

    # a FRESH decoration = a fresh program cache = a cold start in
    # miniature: the miss must deserialize, not re-lower
    got = np.asarray(mpx.spmd(_reduce_step, comm=comm)(x))
    np.testing.assert_array_equal(want, got)
    stats = mpx.cache_stats()["disk_cache"]
    assert stats["hits"] >= 1
    assert stats["misses"] == 0


@needs_serialization
@pytest.mark.slow
def test_cold_start_second_process_served_from_disk(tmp_path):
    """The multi-host cold-start contract in miniature: a SECOND process
    pinning the identical program must deserialize (hits > 0, zero
    misses — zero re-lowers)."""
    script = textwrap.dedent("""
        import json
        import jax.numpy as jnp
        import mpi4jax_tpu as mpx

        comm = mpx.get_default_comm()
        k = comm.Get_size()

        def f(v):
            return mpx.varying(mpx.allreduce(v, op=mpx.SUM)[0] * 0.5)

        x = jnp.full((k, 16), 1.5, jnp.float32)
        pinned = mpx.compile(f, x, comm=comm)
        out = pinned(x)
        assert float(out[0, 0]) == k * 1.5 * 0.5
        print(json.dumps({"from_disk": pinned.from_disk,
                          **{k2: v for k2, v in
                             mpx.cache_stats()["disk_cache"].items()
                             if k2 != "dir"}}))
    """)
    path = tmp_path / "cold_start.py"
    path.write_text(script)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8",
        MPI4JAX_TPU_COMPILE_CACHE_DIR=str(tmp_path / "cache"),
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )

    def run():
        out = subprocess.run(
            [sys.executable, str(path)], env=env, capture_output=True,
            text=True, timeout=240, check=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert not cold["from_disk"] and cold["writes"] >= 1, cold
    warm = run()
    assert warm["from_disk"], warm
    assert warm["hits"] >= 1 and warm["misses"] == 0, warm


# ---------------------------------------------------------------------------
# staleness: MPX129 + re-pin
# ---------------------------------------------------------------------------


def test_epoch_advance_raises_stale_and_repin_recovers():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    pinned = mpx.compile(_reduce_step, x, comm=comm)
    pinned(x)
    assert not pinned.is_stale()

    el.advance_epoch(world=k, cause="revoke", detail="test")
    assert pinned.is_stale()
    with pytest.raises(mpx.StaleProgramError) as ei:
        pinned(x)
    assert getattr(ei.value, "mpx_code", None) == "MPX129"
    assert "epoch" in str(ei.value)
    assert mpx.cache_stats()["aot"]["stale_raises"] == 1

    fresh = pinned.repin()
    out = np.asarray(fresh(x))
    np.testing.assert_array_equal(out, np.full((k, 4), k * 0.5, np.float32))


def test_config_change_raises_stale_and_repin_recovers():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    pinned = mpx.compile(_reduce_step, x, comm=comm)
    pinned(x)
    mpx.set_telemetry_mode("counters")
    try:
        with pytest.raises(mpx.StaleProgramError, match="MPX129"):
            pinned(x)
        fresh = pinned.repin()
        fresh(x)
    finally:
        mpx.set_telemetry_mode(None)
    # back at the original stamp, the ORIGINAL pin is current again
    # (same stamp == same trace); the re-pin of the counters world is
    # now the stale one
    assert not pinned.is_stale()
    assert fresh.is_stale()


def test_mpx129_through_analyze_and_env_error():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    pinned = mpx.compile(_reduce_step, x, comm=comm)

    # negative: a current pin executes clean under the ambient error mode
    mpx.set_analyze_mode("error")
    pinned(x)

    el.advance_epoch(world=k, cause="revoke", detail="test")

    # positive, env=error path: the direct call refuses with the tagged
    # error regardless of mode
    with pytest.raises(mpx.StaleProgramError, match="MPX129"):
        pinned(x)
    mpx.set_analyze_mode(None)

    # positive, analyze path: the tagged raise becomes a finding
    def caller(v):
        return pinned(v)

    report = mpx.analyze(caller, x, wrap=False)
    assert any(f.code == "MPX129" for f in report.findings), report.render()


# ---------------------------------------------------------------------------
# MPX128: the unpinned-hot-loop advisory, traced
# ---------------------------------------------------------------------------


def _hot_loop_fn(n):
    # callable reduction: never fuses (so MPX111 stays quiet and the
    # advisory under test is exactly MPX128), still counts as one
    # repeated (op, comm, statics) signature
    def fn(v):
        out = v
        for _ in range(n):
            out = mpx.varying(mpx.allreduce(out, op=jnp.maximum)[0])
        return out

    return fn


def test_mpx128_through_analyze_positive_and_negative():
    from mpi4jax_tpu.analysis.checkers import AOT_ADVISORY_MIN_REPEATS as N

    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    report = mpx.analyze(_hot_loop_fn(N), x, comm=comm)
    assert any(f.code == "MPX128" for f in report.findings), report.render()
    report = mpx.analyze(_hot_loop_fn(N - 1), x, comm=comm)
    assert not any(f.code == "MPX128" for f in report.findings)


def test_mpx128_env_error_fires_and_pinning_is_exempt():
    from mpi4jax_tpu.analysis.checkers import AOT_ADVISORY_MIN_REPEATS as N

    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    mpx.set_analyze_mode("error")
    try:
        with pytest.raises(mpx.AnalysisError, match="MPX128"):
            mpx.spmd(_hot_loop_fn(N), comm=comm)(x)
        mpx.clear_caches()
        # the SAME hot loop under the pinner is exempt (it is being
        # pinned — the advisory's advice is already taken)
        pinned = mpx.compile(_hot_loop_fn(N), x, comm=comm)
        pinned(x)
    finally:
        mpx.set_analyze_mode(None)


# ---------------------------------------------------------------------------
# the elastic re-pin drill
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_elastic_run_repins_across_shrink():
    """The acceptance drill: an elastic loop whose step is a PINNED
    program survives a shrink — the old pin refuses the new world with
    MPX129, ``mpx.elastic.run`` re-pins transparently, and the run
    finishes the full budget on 7 ranks with a second pin on record."""
    steps, fail_at = 8, 4
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    worlds = []

    def base(state, step_scalar, comm):
        # per-rank step: grad-style allreduce + update (replicated state)
        g, _ = mpx.allreduce(state["p"] * 0.01, op=mpx.SUM, comm=comm)
        return {"p": mpx.varying(state["p"] - g / comm.uniform_size())}

    class Drill:
        """The user-side wrapper pattern: bookkeeping + fault injection
        around the pinned step, exposing repin() for elastic.run."""

        def __init__(self):
            self.inner = mpx.aot.compile_step(base)

        def __call__(self, state, step, comm):
            worlds.append((step, comm.Get_size()))
            if step == fail_at and comm.epoch == 0:
                raise mpx.RankFailure({3}, "simulated")
            return self.inner(state, step, comm)

        def repin(self):
            self.inner.repin()
            return self

    p0 = np.full((3, 2), 1.0, np.float32)
    final = mpx.elastic.run(Drill(), {"p": p0}, store, steps=steps)

    assert el.current_epoch() == 1
    assert store.comm.Get_size() == 7
    # the budget completed on the shrunken world
    assert sorted({s for s, w in worlds if w == 7}) == list(
        range(fail_at, steps))
    stats = mpx.cache_stats()["aot"]
    assert stats["pins"] >= 2, stats          # pre- and post-shrink pins
    assert stats["stale_raises"] >= 1, stats  # the refusal that re-pinned
    assert np.asarray(final["p"]).shape == (3, 2)


def test_compile_step_pins_once_and_raises_on_new_comm():
    comm = _world_comm()

    def base(state, step_scalar, comm):
        s, _ = mpx.allreduce(state["v"], op=mpx.SUM, comm=comm)
        return {"v": mpx.varying(s / comm.uniform_size())}

    step = mpx.aot.compile_step(base)
    s0 = {"v": np.ones((4,), np.float32)}
    s1 = step(s0, 0, comm)
    pins_after_first = mpx.cache_stats()["aot"]["pins"]
    s2 = step(s1, 1, comm)
    assert mpx.cache_stats()["aot"]["pins"] == pins_after_first  # no re-pin
    np.testing.assert_allclose(np.asarray(s2["v"]), np.ones((4,)), rtol=1e-6)

    other = _world_comm()  # a different comm identity = a moved world
    with pytest.raises(mpx.StaleProgramError, match="MPX129"):
        step(s2, 2, other)
    step.repin()
    s3 = step(s2, 2, other)
    np.testing.assert_allclose(np.asarray(s3["v"]), np.ones((4,)), rtol=1e-6)


def test_telemetry_meters_and_report_section():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    mpx.set_telemetry_mode("counters")
    try:
        pinned = mpx.compile(_reduce_step, x, comm=comm)
        pinned(x)
        pinned(x)
        snap = mpx.telemetry.snapshot()
        assert snap["meters"].get("aot.pins") == 1
        assert snap["meters"].get("aot.calls") == 2
        assert "compile_cache" in snap
        assert snap["compile_cache"]["aot"]["calls"] == 2
        text = mpx.telemetry.report(comm=comm, file=open(os.devnull, "w"))
        assert "compile cache:" in text
        assert "2 pinned call(s)" in text
    finally:
        mpx.set_telemetry_mode(None)
