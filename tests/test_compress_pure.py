"""Wire compression: the pure half (docs/compression.md).

The codec byte math (``ops/_codec.py``), the config-layer resolution
(default < tuning < env, payload-bucketed), the ``mpx-tuning/1``
codec-bucket grammar, the cache-token byte-identity pin (off
contributes NOTHING; bf16/fp8 fold and retrace), the cost model's
wire-byte pricing, telemetry's logical/wire DCN split, the EF residual
re-shard plans across elastic reconfigurations, the MPX138 advisory's
positive/negative matrix, and the ``benchmarks/regress.py`` ratchet —
all loaded under a private package name (the tests/test_analysis_pure
isolated loader) so everything here runs even where the installed JAX
is below the package's floor.  The traced integration half — hier
parity per codec, EF convergence, retrace-on-flip, the live telemetry
counters — lives in tests/test_compress.py.
"""

import importlib
import json
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_compress_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops", "analysis", "autotune", "parallel",
                "telemetry"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "autotune.schema", "ops._fusion",
                "ops._codec", "ops._algos", "ops._hierarchy",
                "ops._compress", "telemetry.core", "analysis.report",
                "analysis.graph", "analysis.checkers",
                "analysis.schedule", "analysis.matcher",
                "analysis.progress", "analysis.costmodel",
                "analysis.cost", "parallel.rankspec",
                "parallel.topology"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = sys.modules[f"{_ISO_NAME}.utils.config"]
schema = sys.modules[f"{_ISO_NAME}.autotune.schema"]
codec = sys.modules[f"{_ISO_NAME}.ops._codec"]
algos = sys.modules[f"{_ISO_NAME}.ops._algos"]
hierarchy = sys.modules[f"{_ISO_NAME}.ops._hierarchy"]
compress = sys.modules[f"{_ISO_NAME}.ops._compress"]
telemetry = sys.modules[f"{_ISO_NAME}.telemetry.core"]
cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]

E = graph.CollectiveEvent
G = graph.CollectiveGraph

sys.path.insert(0, str(REPO / "benchmarks"))
import regress  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_layer(monkeypatch):
    """Every test starts with no env override and no tuning layer."""
    monkeypatch.delenv("MPI4JAX_TPU_COMPRESS", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_COMPRESS_ERROR_BUDGET", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_TUNING", raising=False)
    yield
    config.load_tuning(None)


def codes_of(g):
    return [f.code for f in checkers.run_checkers(g)]


# ---------------------------------------------------------------------------
# the byte math (ops/_codec.py) — one truth source for every layer
# ---------------------------------------------------------------------------


def test_wire_bytes_table():
    n = 1 << 20
    assert codec.wire_bytes(n, None) == n
    assert codec.wire_bytes(n, "off") == n
    assert codec.wire_bytes(n, "bf16") == n // 2
    # fp8: 1 byte/element + one f32 scale per 256-element chunk
    elems = n // 4
    assert codec.wire_bytes(n, "fp8") == elems + 4 * (elems // 256)
    # a partial chunk still pays a whole scale
    assert codec.wire_bytes(4 * 257, "fp8") == 257 + 4 * 2
    assert codec.wire_bytes(0, "fp8") == 0
    with pytest.raises(ValueError, match="unknown wire codec"):
        codec.wire_bytes(n, "gzip")


def test_compression_ratio_acceptance_floor():
    # the PR's acceptance ratio: both codecs cut DCN wire bytes >= 2x
    n = 4 << 20
    assert codec.compression_ratio(n, "bf16") == 2.0
    assert codec.compression_ratio(n, "fp8") >= 2.0
    assert codec.compression_ratio(n, "fp8") == pytest.approx(3.94, abs=0.01)
    assert codec.compression_ratio(n, None) == 1.0
    assert codec.compression_ratio(0, "fp8") == 1.0


def test_codec_for_gates(monkeypatch):
    # default: off -> no codec for anything
    assert codec.codec_for(1 << 20, "float32") is None
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    assert codec.codec_for(1 << 20, "float32") == "bf16"
    # float32 only — every other dtype ships exact in every mode
    for dt in ("float64", "int32", "bfloat16", "float16", ""):
        assert codec.codec_for(1 << 20, dt) is None


# ---------------------------------------------------------------------------
# config resolution: default < tuning < env, payload-bucketed
# ---------------------------------------------------------------------------


def test_compress_mode_default_off():
    assert config.compress_mode() == "off"
    assert config.compress_mode(payload_bytes=1 << 30) == "off"


def test_compress_mode_env_wins(monkeypatch):
    config.load_tuning({"schema": "mpx-tuning/1",
                        "tuned": {"compress": "fp8"}})
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    # explicit non-auto env beats the tuned value
    assert config.compress_mode() == "bf16"
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
    assert config.compress_mode() == "off"


def test_compress_mode_auto_resolves(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "auto")
    # auto with no tuning layer: bf16 (the conservative codec)
    assert config.compress_mode() == "bf16"
    # auto with a tuned codec: the measured pick
    config.load_tuning({"schema": "mpx-tuning/1",
                        "tuned": {"compress": "fp8"}})
    assert config.compress_mode() == "fp8"


def test_compress_mode_payload_bucketed():
    config.load_tuning({
        "schema": "mpx-tuning/1",
        "tuned": {"compress": [
            {"max_bytes": 1 << 20, "codec": "off"},
            {"max_bytes": None, "codec": "fp8"},
        ]},
    })
    assert config.compress_mode(payload_bytes=1 << 20) == "off"
    assert config.compress_mode(payload_bytes=(1 << 20) + 1) == "fp8"
    # no payload context: the open-ended bucket answers
    assert config.compress_mode() == "fp8"


def test_compress_error_budget(monkeypatch):
    assert config.compress_error_budget() == 1e-2
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS_ERROR_BUDGET", "0.05")
    assert config.compress_error_budget() == 0.05
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS_ERROR_BUDGET", "-1")
    with pytest.raises(ValueError):
        config.compress_error_budget()


def test_flags_registered():
    assert "MPI4JAX_TPU_COMPRESS" in config.FLAGS
    assert "MPI4JAX_TPU_COMPRESS_ERROR_BUDGET" in config.FLAGS
    assert schema.KNOB_FLAGS["compress"] == "MPI4JAX_TPU_COMPRESS"


def test_tuning_snapshot_carries_compress(monkeypatch):
    config.load_tuning({"schema": "mpx-tuning/1",
                        "tuned": {"compress": "bf16"}})
    snap = config.tuning_snapshot()
    knob = snap["knobs"]["compress"]
    assert knob["tuned"] == "bf16"
    assert knob["default"] == "off"
    assert knob["effective"] == "bf16"
    assert knob["env_wins"] is False
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
    knob = config.tuning_snapshot()["knobs"]["compress"]
    assert knob["env_wins"] is True and knob["effective"] == "off"


# ---------------------------------------------------------------------------
# the mpx-tuning/1 codec-bucket grammar
# ---------------------------------------------------------------------------


def test_schema_accepts_codec_values():
    for val in ("off", "bf16", "fp8",
                [{"max_bytes": 1024, "codec": "off"},
                 {"max_bytes": None, "codec": "bf16"}]):
        schema.validate_tuning_dict(
            {"schema": "mpx-tuning/1", "tuned": {"compress": val}})


def test_schema_rejects_bad_codecs():
    for val in ("gzip", "auto2", 7,
                [{"max_bytes": 1024, "codec": "zstd"}],
                [{"max_bytes": 1024}],
                [{"max_bytes": 2048, "codec": "off"},
                 {"max_bytes": 1024, "codec": "bf16"}]):
        with pytest.raises(ValueError):
            schema.validate_tuning_dict(
                {"schema": "mpx-tuning/1", "tuned": {"compress": val}})


def test_tuning_knob_bucket_lookup():
    tf = schema.as_tuning({
        "schema": "mpx-tuning/1",
        "tuned": {"compress": [
            {"max_bytes": 4096, "codec": "off"},
            {"max_bytes": None, "codec": "fp8"},
        ]},
    })
    assert tf.knob("compress", payload_bytes=4096) == "off"
    assert tf.knob("compress", payload_bytes=4097) == "fp8"
    assert tf.knob("compress") == "fp8"  # open-ended bucket


# ---------------------------------------------------------------------------
# cache token: off is byte-identical, a codec folds and retraces
# ---------------------------------------------------------------------------


def test_cache_token_off_is_the_pre_compression_tuple():
    # the byte-identity pin: with the knob off (the default) the token
    # is EXACTLY the flat pre-compression 5-tuple — no trailing entry,
    # so cache keys (and the HLO they key) never move on upgrade
    tok = algos.algo_cache_token()
    assert len(tok) == 5
    assert "compress" not in str(tok)


def test_cache_token_folds_active_codec(monkeypatch):
    base = algos.algo_cache_token()
    for mode in ("bf16", "fp8"):
        monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", mode)
        tok = algos.algo_cache_token()
        assert tok != base  # flipping the knob retraces
        assert tok[:5] == base
        assert ("compress", mode) in tok
    # auto resolves before folding: the token carries the CONCRETE codec
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "auto")
    assert ("compress", "bf16") in algos.algo_cache_token()


# ---------------------------------------------------------------------------
# DCN-leg selection math (ops/_hierarchy.py)
# ---------------------------------------------------------------------------


def test_dcn_leg_bytes():
    # reduction family: the inter phase moves payload/r per host pair
    assert hierarchy.dcn_leg_bytes("allreduce", 4096, 4) == 1024
    assert hierarchy.dcn_leg_bytes("reduce_scatter", 4097, 4) == 1025
    # alltoall: the host-aggregated exchange ships the full payload
    assert hierarchy.dcn_leg_bytes("alltoall", 4096, 4) == 4096


def test_selected_codec_respects_payload_bucket(monkeypatch):
    config.load_tuning({
        "schema": "mpx-tuning/1",
        "tuned": {"compress": [
            {"max_bytes": 1024, "codec": "off"},
            {"max_bytes": None, "codec": "bf16"},
        ]},
    })
    plan = hierarchy.HierPlan(None, None, 2, 4)
    # the codec resolves on the DCN-LEG bytes, not the logical payload:
    # 4096 logical / r=4 = 1024 per-leg -> below the bucket, exact
    assert hierarchy.selected_codec("allreduce", 4096, plan,
                                    dtype="float32") is None
    assert hierarchy.selected_codec("allreduce", 8192, plan,
                                    dtype="float32") == "bf16"
    # alltoall's leg is the whole payload
    assert hierarchy.selected_codec("alltoall", 4096, plan,
                                    dtype="float32") == "bf16"
    assert hierarchy.selected_codec("allreduce", 8192, plan,
                                    dtype="int32") is None
    # flat lowering / order-preserving callables always ship exact
    assert hierarchy.selected_codec("allreduce", 8192, None,
                                    dtype="float32") is None
    assert hierarchy.selected_codec("allreduce", 8192, plan,
                                    preserve=True,
                                    dtype="float32") is None


# ---------------------------------------------------------------------------
# cost model prices the WIRE bytes of a compressed DCN leg
# ---------------------------------------------------------------------------


def test_collective_cost_codec_prices_wire_bytes():
    n, k, h = 1 << 20, 8, 2
    exact = cm.collective_cost("allreduce", "hier", n, k, hosts=h,
                               hier=(h, 4))
    for c in ("bf16", "fp8"):
        priced = cm.collective_cost("allreduce", "hier", n, k, hosts=h,
                                    hier=(h, 4), codec=c)
        assert priced.dcn.nbytes == codec.wire_bytes(exact.dcn.nbytes, c)
        assert priced.dcn.rounds == exact.dcn.rounds
        # ICI phases stay exact in every mode
        assert priced.ici.nbytes == exact.ici.nbytes
        assert priced.ici.rounds == exact.ici.rounds
    # codec=None / "off" is the identity
    off = cm.collective_cost("allreduce", "hier", n, k, hosts=h,
                             hier=(h, 4), codec=None)
    assert off.dcn.nbytes == exact.dcn.nbytes


def test_collective_cost_codec_alltoall():
    n, k, h = 1 << 20, 8, 2
    exact = cm.collective_cost("alltoall", "hier", n, k, hosts=h,
                               hier=(h, 4))
    priced = cm.collective_cost("alltoall", "hier", n, k, hosts=h,
                                hier=(h, 4), codec="bf16")
    assert priced.dcn.nbytes == exact.dcn.nbytes // 2


# ---------------------------------------------------------------------------
# telemetry: the logical/wire DCN byte split
# ---------------------------------------------------------------------------


def test_count_op_wire_split():
    t = telemetry._Counters()
    t.count_op("allreduce|1|hier|float32", 4096, intra=3072, inter=1024,
               wire_inter=512)
    t.count_op("allreduce|1|hier|float32", 4096, intra=3072, inter=1024,
               wire_inter=512)
    row = t.ops["allreduce|1|hier|float32"]
    assert row["inter_bytes"] == 2048
    assert row["wire_inter_bytes"] == 1024


def test_count_op_wire_defaults_to_logical():
    # un-annotated ops report wire == logical (exact transport)
    t = telemetry._Counters()
    t.count_op("bcast|1|native|int32", 4096, intra=4096, inter=128)
    row = t.ops["bcast|1|native|int32"]
    assert row["wire_inter_bytes"] == row["inter_bytes"] == 128


# ---------------------------------------------------------------------------
# EF residual re-shard plans across elastic reconfigurations
# ---------------------------------------------------------------------------


def test_ef_reshard_rows_shrink():
    # 4-rank world loses rank 1: compaction {0:0, 2:1, 3:2}
    rows = codec.ef_reshard_rows(4, {0: 0, 2: 1, 3: 2}, 3)
    assert rows == [0, 2, 3]  # each NEW rank carries its OLD row


def test_ef_reshard_rows_grow_zeroes_joiners():
    # 3-rank world grows back to 4: identity map, joiner row is None
    rows = codec.ef_reshard_rows(3, {0: 0, 1: 1, 2: 2}, 4)
    assert rows == [0, 1, 2, None]  # None = MUST be zeroed, never stale


def test_ef_reshard_rows_validates():
    with pytest.raises(ValueError, match="new_world"):
        codec.ef_reshard_rows(2, {0: 0}, 0)
    with pytest.raises(ValueError, match="out of range"):
        codec.ef_reshard_rows(2, {5: 0}, 2)
    # a mapping landing outside the new world is simply dropped
    assert codec.ef_reshard_rows(3, {0: 0, 2: 7}, 2) == [0, None]


def test_ef_reshard_moves_rows_and_zeroes():
    res = {"w": np.arange(12, dtype=np.float32).reshape(4, 3)}
    out = compress.ef_reshard(res, {0: 0, 2: 1, 3: 2}, 3)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  res["w"][[0, 2, 3]])
    grown = compress.ef_reshard(out, {0: 0, 1: 1, 2: 2}, 4)
    np.testing.assert_array_equal(np.asarray(grown["w"][3]),
                                  np.zeros(3, np.float32))


def test_ef_zeros_like_and_roundtrip_identity():
    tree = {"a": np.ones((2, 3), np.float32)}
    z = compress.ef_zeros_like(tree)
    assert float(np.sum(np.abs(np.asarray(z["a"])))) == 0.0
    x = np.linspace(-1, 1, 64, dtype=np.float32)
    import jax.numpy as jnp

    xv = jnp.asarray(x)
    np.testing.assert_array_equal(np.asarray(compress.roundtrip(xv, None)),
                                  x)
    np.testing.assert_array_equal(np.asarray(compress.roundtrip(xv, "off")),
                                  x)
    # bf16 roundtrip error is bounded by the 2^-8 mantissa step
    y = np.asarray(compress.roundtrip(xv, "bf16"))
    assert float(np.max(np.abs(y - x))) <= 2.0 ** -8
    # fp8 roundtrip error bounded by the per-chunk scale * e4m3 step
    y8 = np.asarray(compress.roundtrip(xv, "fp8"))
    assert float(np.max(np.abs(y8 - x))) <= 1.0 / 8
    with pytest.raises(ValueError, match="unknown wire codec"):
        compress.roundtrip(xv, "gzip")


# ---------------------------------------------------------------------------
# MPX138 — uncompressed DCN leg above the crossover
# ---------------------------------------------------------------------------


_C_META = {"compress": "off", "dcn_crossover_bytes": 1024}


def _hier_ev(op="allreduce", payload=8192, comm_size=8, hosts=2, **kw):
    return E(0, op, comm_uid=1, comm_size=comm_size, hosts=hosts,
             payload_bytes=payload, algo="hier",
             hier=(hosts, comm_size // hosts) if hosts else None, **kw)


def test_mpx138_fires_on_uncompressed_hier_leg():
    g = G(events=[_hier_ev()], meta=dict(_C_META))
    found = [f for f in checkers.run_checkers(g) if f.code == "MPX138"]
    assert len(found) == 1
    f = found[0]
    assert f.severity == "advisory"
    # leg = ceil(8192 / r=4) = 2048 — the per-leg bytes, not the payload
    assert "2048 B" in f.message and "1024 B" in f.message
    assert "MPI4JAX_TPU_COMPRESS=bf16" in f.message
    assert "docs/compression.md" in f.suggestion
    assert "ef_allreduce" in f.suggestion


def test_mpx138_alltoall_leg_is_the_full_payload():
    # alltoall ships the whole payload over DCN: payload 2048 fires at
    # crossover 1024 even though 2048/r would not
    g = G(events=[_hier_ev(op="alltoall", payload=2048)],
          meta={"compress": "off", "dcn_crossover_bytes": 1025})
    assert "MPX138" in codes_of(g)
    g = G(events=[_hier_ev(op="allreduce", payload=2048)],
          meta={"compress": "off", "dcn_crossover_bytes": 1025})
    assert "MPX138" not in codes_of(g)  # leg = 512 < 1025


def test_mpx138_async_start_counts():
    g = G(events=[_hier_ev(op="allreduce_start", span=3)],
          meta=dict(_C_META))
    assert "MPX138" in codes_of(g)


def test_mpx138_cites_measured_crossover():
    meta = {"compress": "off", "dcn_crossover_bytes": 1 << 30,
            "measured_dcn_crossover_bytes": 1024,
            "tuned_stamp": "abc123def456"}
    g = G(events=[_hier_ev()], meta=meta)
    (f,) = [x for x in checkers.run_checkers(g) if x.code == "MPX138"]
    assert "measured DCN crossover" in f.message
    assert "tuned@abc123def456" in f.message


def test_mpx138_negatives():
    # the layer is already on: the user made the choice
    g = G(events=[_hier_ev()],
          meta={"compress": "bf16", "dcn_crossover_bytes": 1024})
    assert "MPX138" not in codes_of(g)
    # THIS event already compressed
    g = G(events=[_hier_ev(codec="bf16")], meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # flat algorithm: MPX113's territory, not a codec question
    g = G(events=[E(0, "allreduce", comm_uid=1, comm_size=8, hosts=2,
                    payload_bytes=8192, algo="ring")],
          meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # non-float32 payloads ship exact in every mode
    g = G(events=[_hier_ev(dtype="int32")], meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # below the crossover: compression cannot pay
    g = G(events=[_hier_ev(payload=256)], meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # single-host comm: no DCN leg exists
    g = G(events=[_hier_ev(hosts=1)], meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # one rank per host: the hierarchy degenerates
    g = G(events=[_hier_ev(comm_size=2, hosts=2)], meta=dict(_C_META))
    assert "MPX138" not in codes_of(g)
    # hand-built graph without the crossover meta: other rules' tests
    g = G(events=[_hier_ev()])
    assert "MPX138" not in codes_of(g)


def test_mpx138_in_catalog():
    report = sys.modules[f"{_ISO_NAME}.analysis.report"]
    assert any("MPX138" in codes for codes, _fn in checkers.CHECKERS)
    info = report.CODES["MPX138"]
    assert info.severity == report.ADVISORY
    assert "MPI4JAX_TPU_COMPRESS" in info.doc


# ---------------------------------------------------------------------------
# benchmarks/regress.py — the perf ratchet
# ---------------------------------------------------------------------------


def test_regress_collect_keys_rows_by_identity():
    payload = {"sweep": [
        {"size_mb": 1.0, "codec": "off", "modeled_dcn_us": 10.0},
        {"size_mb": 1.0, "codec": "bf16", "modeled_dcn_us": 5.0},
    ]}
    cols = regress.collect(payload, "_us")
    assert len(cols) == 2
    # keyed by discriminating columns, not list position
    reordered = {"sweep": list(reversed(payload["sweep"]))}
    assert regress.collect(reordered, "_us") == cols


def test_regress_compare_thresholds():
    base = {"a": [{"op": "x", "t_us": 100.0}, {"op": "y", "t_us": 100.0}]}
    cur = {"a": [{"op": "x", "t_us": 109.0}, {"op": "y", "t_us": 112.0}]}
    reg, imp, only_c, only_b = regress.compare(cur, base, threshold=0.10)
    assert len(reg) == 1  # only the 12% column trips the 10% ratchet
    assert not imp and not only_c and not only_b
    # improvements and one-sided columns never fail the run
    cur2 = {"a": [{"op": "x", "t_us": 50.0}, {"op": "z", "t_us": 1.0}]}
    reg, imp, only_c, only_b = regress.compare(cur2, base, threshold=0.10)
    assert not reg and len(imp) == 1
    assert len(only_c) == 1 and len(only_b) == 1


def test_regress_ignores_non_suffix_and_bools():
    base = {"r": [{"op": "x", "t_us": 10.0, "bytes": 100, "ok": True}]}
    cols = regress.collect(base, "_us")
    assert list(cols.values()) == [10.0]


def test_regress_main_exit_codes(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(
        {"s": [{"op": "x", "t_us": 100.0}]}))
    cur.write_text(json.dumps(
        {"s": [{"op": "x", "t_us": 105.0}]}))
    assert regress.main(["--current", str(cur),
                         "--baseline", str(base)]) == 0
    cur.write_text(json.dumps(
        {"s": [{"op": "x", "t_us": 150.0}]}))
    assert regress.main(["--current", str(cur),
                         "--baseline", str(base)]) == 1
    # tighter threshold flips a pass into a regression
    cur.write_text(json.dumps(
        {"s": [{"op": "x", "t_us": 105.0}]}))
    assert regress.main(["--current", str(cur), "--baseline", str(base),
                         "--threshold", "0.01"]) == 1
    # IO / usage errors are exit 2, the analysis CLI's contract
    assert regress.main(["--current", str(tmp_path / "missing.json"),
                         "--baseline", str(base)]) == 2
    assert regress.main(["--current", str(cur), "--baseline", str(base),
                         "--threshold", "-1"]) == 2


def test_regress_ratchets_the_committed_artifacts():
    # the committed BENCH_* replays regress-check against themselves
    # cleanly — the CI smoke lane's invocation shape
    for name in ("BENCH_compress.json", "BENCH_alltoall.json"):
        path = str(REPO / name)
        assert regress.main(["--current", path,
                             "--baseline", path]) == 0


# ---------------------------------------------------------------------------
# the committed convergence artifact (capture-time claims re-checked)
# ---------------------------------------------------------------------------


def test_bench_compress_artifact_claims():
    payload = json.loads((REPO / "BENCH_compress.json").read_text())
    assert payload["schema"] == "mpx-compress-replay/1"
    off_rows = {(r["size_mb"], r["topology"]): r
                for r in payload["wire_sweep"] if r["codec"] == "off"}
    for row in payload["wire_sweep"]:
        if row["codec"] == "off":
            assert row["wire_dcn_bytes"] == row["logical_dcn_bytes"]
            continue
        # the acceptance floor: >= 2x modeled DCN wire-byte reduction
        assert row["wire_reduction"] >= 2.0, row
        assert row["wire_dcn_bytes"] == codec.wire_bytes(
            row["logical_dcn_bytes"], row["codec"])
        off = off_rows[(row["size_mb"], row["topology"])]
        assert row["modeled_dcn_us"] < off["modeled_dcn_us"]
    conv = payload["convergence"]
    exact = conv["curves"]["off"]
    for name, p in conv["parity"].items():
        assert p["max_rel_gap"] <= p["tolerance"], (name, p)
        curve = conv["curves"][name]
        assert len(curve) == len(exact)
    # every codec's replay converged by orders of magnitude
    for name in ("off", "bf16", "fp8"):
        c = conv["curves"][name]
        assert c[-1] < c[0] * 1e-2, name
