"""profile_ops: the TPU-side per-op latency story (SURVEY.md §5 tracing).

The measured host-bracket path (MPI4JAX_TPU_TRACE) is CPU-backend-only by
design; on TPU the measured source is the device profiler.  ``profile_ops``
packages the capture protocol (async-dispatch fence before the trace
closes) — this file pins that a trace of a program full of collectives
actually lands on disk with content, on the test backend; the chip lane's
recipe is the same call (docs/usage.md).
"""

import glob

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as mpx


def test_profile_ops_captures_trace(tmp_path):
    comm = mpx.get_default_comm()

    @mpx.spmd
    def step(x):
        y, tok = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        z, _ = mpx.sendrecv(y, y, dest=mpx.shift(1), comm=comm, token=tok)
        return z

    x = jnp.ones((8, 64))
    step(x)  # compile outside the capture window
    logdir = str(tmp_path / "trace")
    with mpx.profile_ops(logdir):
        out = step(x)
    # the fence ran inside the context: out is ready without further sync
    assert np.isfinite(np.asarray(out)).all()
    files = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    assert files, f"no trace captured under {logdir}"


def test_profile_ops_summary_reports_fence(tmp_path):
    """The yielded summary proves the exit fence ran: it names the trace
    dir/backend and counts the live arrays it blocked on (scoped to the
    DEFAULT backend — a sidecar array on another backend must not stall
    the close)."""
    logdir = str(tmp_path / "trace_summary")
    with mpx.profile_ops(logdir) as prof:
        out = jnp.ones((8, 16)) * 2
    assert prof.trace_dir == logdir
    assert prof.backend == "cpu"
    # `out` is live at exit, so the fence had at least it to block on
    assert prof.fenced_arrays >= 1
    assert np.isfinite(np.asarray(out)).all()
    assert "fenced_arrays=" in repr(prof)


def test_profile_ops_nested_exceptions_close_trace(tmp_path):
    """An exception inside the window must not leave the profiler running
    (a dangling session would poison every later capture)."""
    logdir = str(tmp_path / "trace2")
    try:
        with mpx.profile_ops(logdir):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    # a second capture works — the first session was closed
    with mpx.profile_ops(logdir):
        jnp.ones(4).sum()
    assert glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
