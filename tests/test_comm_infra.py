"""Comm structure, eager mode, dtypes, validation, config, capability probes.

Ports ref tests/test_validation.py, test_decorators.py (env parsing),
test_has_cuda.py / test_has_sycl.py (probes), and the comm-handling parts of
test_common.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.utils.config import parse_env_bool
from helpers import ranks_arange, world


def test_comm_size_rank():
    comm, size = world()
    assert comm.Get_size() == jax.device_count()

    @mpx.spmd
    def f(x):
        r = mpx.get_default_comm().Get_rank()
        return x * 0 + r

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.arange(size))


def test_comm_clone_distinct_uid():
    comm, _ = world()
    clone = comm.Clone()
    assert clone.uid != comm.uid
    assert clone.axes == comm.axes
    assert clone.Get_size() == comm.Get_size()


def test_comm_2d_mesh_sub():
    mesh = mpx.make_world_mesh((4, 2), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)
    assert comm.Get_size() == 8

    @mpx.spmd(comm=comm)
    def f(xl):
        row = mpx.get_default_comm().sub("x")
        col = mpx.get_default_comm().sub("y")
        rs, _ = mpx.allreduce(xl, op=mpx.SUM, comm=row)
        cs, _ = mpx.allreduce(xl, op=mpx.SUM, comm=col)
        return rs, cs

    x = jnp.arange(8.0)[:, None]
    rs, cs = f(x)
    rs, cs = np.asarray(rs)[:, 0], np.asarray(cs)[:, 0]
    # row-major (y,x): linear rank r has y=r//2, x=r%2
    assert np.allclose(rs, [1, 1, 5, 5, 9, 9, 13, 13])  # sums over x
    assert np.allclose(cs, [12, 16, 12, 16, 12, 16, 12, 16])  # sums over y


def test_comm_multi_axis_allreduce():
    mesh = mpx.make_world_mesh((4, 2), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)

    @mpx.spmd(comm=comm)
    def f(xl):
        s, _ = mpx.allreduce(xl, op=mpx.SUM)
        return s

    out = np.asarray(f(jnp.arange(8.0)[:, None]))
    assert np.allclose(out, 28.0)


def test_comm_rank_row_major():
    mesh = mpx.make_world_mesh((4, 2), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)

    @mpx.spmd(comm=comm)
    def f(xl):
        return xl * 0 + comm.Get_rank()

    out = np.asarray(f(jnp.zeros((8, 1))))[:, 0]
    assert np.allclose(out, np.arange(8))


def test_p2p_on_multi_axis_comm():
    """p2p over a multi-axis comm rides the linearized row-major rank
    order: shift(1) on a (4, 2) comm is one ring over all 8 devices
    (before round 5 this raised 'requires a single-axis communicator')."""
    mesh = mpx.make_world_mesh((4, 2), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)

    @mpx.spmd(comm=comm)
    def f(xl):
        y, _ = mpx.sendrecv(xl, xl, dest=mpx.shift(1))
        return y

    out = np.asarray(f(jnp.arange(8.0)[:, None])).ravel()
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_unbound_comm_error():
    comm = mpx.Comm("nonexistent_axis")
    with pytest.raises(RuntimeError, match="not bound"):
        comm.Get_size()


def test_eager_wrong_leading_axis():
    with pytest.raises(ValueError, match="leading rank axis"):
        mpx.allreduce(jnp.zeros((3, 2)))


def test_eager_token_roundtrip():
    x = ranks_arange((2,))
    res, token = mpx.allreduce(x)
    res2, token2 = mpx.allreduce(x, token=token)
    assert np.allclose(np.asarray(res), np.asarray(res2))


def test_unsupported_dtype():
    # f64 works on CPU; check the rejection path with a genuinely
    # unsupported width via a numpy structured view is overkill — use
    # float128 if the platform has it
    if not hasattr(np, "float128"):
        pytest.skip("platform lacks float128")
    x = np.zeros((8, 2), dtype=np.float128)
    with pytest.raises((TypeError, ValueError)):
        @mpx.spmd
        def f(xl):
            return mpx.allreduce(xl)[0]

        f(x)


def test_parse_env_bool(monkeypatch):
    # ref tests/test_decorators.py truthy-env parsing.  Reads go through
    # the declared-flag registry (utils/config.py FLAGS), so the probe
    # flag is declared for the duration of the test.
    from mpi4jax_tpu.utils import config as _config

    monkeypatch.setitem(
        _config.FLAGS, "MPI4JAX_TPU_TESTFLAG",
        _config.Flag("MPI4JAX_TPU_TESTFLAG", "bool", False, "test probe"),
    )
    for v in ("1", "true", "ON", "yes"):
        monkeypatch.setenv("MPI4JAX_TPU_TESTFLAG", v)
        assert parse_env_bool("MPI4JAX_TPU_TESTFLAG") is True
    for v in ("0", "false", "OFF", "no", ""):
        monkeypatch.setenv("MPI4JAX_TPU_TESTFLAG", v)
        assert parse_env_bool("MPI4JAX_TPU_TESTFLAG") is False
    monkeypatch.setenv("MPI4JAX_TPU_TESTFLAG", "maybe")
    with pytest.raises(ValueError, match="could not be parsed"):
        parse_env_bool("MPI4JAX_TPU_TESTFLAG")
    monkeypatch.delenv("MPI4JAX_TPU_TESTFLAG")
    assert parse_env_bool("MPI4JAX_TPU_TESTFLAG", True) is True


def test_undeclared_flag_read_raises(monkeypatch):
    # the registry is the single read point: undeclared MPI4JAX_TPU_*
    # reads fail loudly (and are a lint failure — tests/test_lint.py)
    with pytest.raises(RuntimeError, match="not declared"):
        parse_env_bool("MPI4JAX_TPU_NOT_A_FLAG")


def test_capability_probes():
    # ref tests/test_has_cuda.py / test_has_sycl.py
    assert mpx.has_cuda_support() in (True, False)
    assert mpx.has_tpu_support() in (True, False)
    assert mpx.has_sycl_support() is False
    # CPU test backend: no cuda/tpu
    assert not mpx.has_cuda_support()


def test_public_api_surface():
    # the reference's 12 ops + probes (ref mpi4jax/__init__.py:26-41) must
    # all be importable from the top level
    for name in [
        "allgather", "allreduce", "alltoall", "barrier", "bcast", "gather",
        "recv", "reduce", "scan", "scatter", "send", "sendrecv",
        "has_cuda_support", "has_sycl_support", "has_tpu_support",
    ]:
        assert hasattr(mpx, name), name


def test_debug_logging_format(capfd):
    # ref tests/collective_ops/test_common.py:118-144 — debug log format
    # r{rank} | {8 hex} | MPI_X asserted on captured output
    import re

    from mpi4jax_tpu.utils import debug

    debug.set_logging(True)
    try:
        @mpx.spmd
        def f(x):
            res, _ = mpx.allreduce(x, op=mpx.SUM)
            return res

        out = f(ranks_arange((1,)))
        out.block_until_ready()
        jax.effects_barrier()
    finally:
        debug.set_logging(False)
    captured = capfd.readouterr()
    text = captured.out + captured.err
    assert re.search(r"r\d+ \| [0-9a-f]{8} \| MPI_Allreduce", text), text[:500]


def test_logging_toggle_busts_spmd_program_cache(capfd):
    # Regression (ADVICE r1): the spmd program cache must key on the
    # dynamically-read observability flags — enabling logging *after* a
    # wrapped function's first call must re-trace, not silently serve the
    # stale silent program.
    import re

    from mpi4jax_tpu.utils import debug

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    out = f(ranks_arange((1,)))
    out.block_until_ready()
    jax.effects_barrier()
    capfd.readouterr()  # discard pre-toggle output

    debug.set_logging(True)
    try:
        out = f(ranks_arange((1,)))
        out.block_until_ready()
        jax.effects_barrier()
    finally:
        debug.set_logging(False)
    text = capfd.readouterr()
    text = text.out + text.err
    assert re.search(r"r\d+ \| [0-9a-f]{8} \| MPI_Allreduce", text), text[:500]


def test_wallclock_fallback_without_native_lib(monkeypatch):
    # Regression (ADVICE r1): the pure-Python wallclock fallback declared a
    # float64 pure_callback result, which raises under the default
    # x64-disabled config. It must work and match the FFI path's dtype.
    from mpi4jax_tpu import native

    monkeypatch.setattr(native, "runtime_tracing_supported", lambda: False)
    t0 = jax.jit(native.wallclock)()
    t1 = jax.jit(native.wallclock)()
    expect = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    assert t0.dtype == expect
    assert float(t1) >= float(t0)

    # two reads inside ONE jit must not be deduped into a single host call
    def elapsed():
        a = native.wallclock()
        b = native.wallclock(dep=a)
        return a, b

    a, b = jax.jit(elapsed)()
    assert float(b) >= float(a)


def test_axis_bound_probe():
    # Pins the two behaviors in_parallel_region relies on (a JAX upgrade
    # that changes either must fail HERE, not silently reroute every
    # in-region op through the eager path):
    # 1. the private axis-env probe agrees with reality in and out of
    #    shard_map;
    # 2. the fallback contract — lax.axis_size raises NameError (not some
    #    other exception) for an unbound axis.
    from jax import lax

    from mpi4jax_tpu.utils.jax_compat import axis_bound

    comm, _ = world()
    axis = comm.axes[0]

    assert not axis_bound(axis)
    assert not mpx.parallel.region.in_parallel_region(comm)

    with pytest.raises(NameError, match="unbound axis"):
        lax.axis_size("definitely-not-an-axis")

    seen = {}

    @mpx.spmd
    def f(x):
        seen["inside"] = axis_bound(axis)
        seen["region"] = mpx.parallel.region.in_parallel_region(comm)
        return x

    f(ranks_arange((1,)))
    assert seen["inside"] is True
    assert seen["region"] is True
