"""Version advisory tests (ref tests/test_jax_compat.py: version-tuple
parsing and warning behavior via monkeypatch)."""

import warnings

import pytest

from mpi4jax_tpu.utils.jax_compat import (
    LATEST_JAX_VERSION,
    MIN_JAX_VERSION,
    check_jax_version,
    versiontuple,
)


@pytest.mark.parametrize(
    "raw, expected",
    [
        ("0.9.0", (0, 9, 0)),
        ("0.4.24", (0, 4, 24)),
        ("0.10.0.dev20260101", (0, 10, 0)),
        ("1.0.0rc1", (1, 0, 0)),
    ],
)
def test_versiontuple(raw, expected):
    assert versiontuple(raw) == expected


def test_in_range_version_passes_silently():
    # explicit in-range version: keeps CI green when a newer jax ships
    # (the advisory for the *installed* jax is informational, not an error)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        check_jax_version(LATEST_JAX_VERSION)
        check_jax_version(MIN_JAX_VERSION)


def test_newer_jax_warns():
    with pytest.warns(UserWarning, match="latest supported JAX version"):
        check_jax_version("99.0.0")


def test_newer_jax_warning_silenced(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_NO_WARN_JAX_VERSION", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        check_jax_version("99.0.0")


def test_too_old_jax_raises():
    with pytest.raises(RuntimeError, match="requires jax>="):
        check_jax_version("0.4.24")


def test_bounds_are_ordered():
    assert versiontuple(MIN_JAX_VERSION) <= versiontuple(LATEST_JAX_VERSION)
