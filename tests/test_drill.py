"""Chaos-drill harness suite (resilience/drill.py + the committed
BENCH_elastic.json).

Runs under the isolated loader (no mpi4jax_tpu import, any JAX): the
drills are pure simulation by design.  Tier-1 covers the 8/16-rank
matrix and the two host-row acceptance topologies; the 64-rank matrix
and the committed-artifact reproducibility diff ride the slow tier
(the CI ``elastic-drill`` step).
"""

import importlib
import json
import pathlib
import subprocess
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_drill_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "resilience.faultinject",
        "resilience.retry",
        "resilience.watchdog",
        "resilience.elastic",
        "resilience.drill",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
drill = ISO.resilience.drill
el = ISO.resilience.elastic


# ---------------------------------------------------------------------------
# harness plumbing
# ---------------------------------------------------------------------------


def test_default_counts_are_square_uniform_splits():
    assert drill.default_counts(8) == (4, 4)
    assert drill.default_counts(16) == (4, 4, 4, 4)
    assert drill.default_counts(64) == (8,) * 8
    assert sum(drill.default_counts(12)) == 12
    with pytest.raises(ValueError):
        drill.default_counts(0)


def test_kill_sets_per_pattern():
    counts = (4, 4)
    assert drill.kill_set("single", 8, counts) == (4,)
    assert drill.kill_set("coordinator", 8, counts) == (0,)
    assert drill.kill_set("host-row", 8, counts) == (4, 5, 6, 7)
    assert drill.kill_set("double", 8, counts) == (4,)
    with pytest.raises(ValueError, match="unknown drill pattern"):
        drill.kill_set("meteor", 8, counts)
    with pytest.raises(ValueError, match=">= 2 hosts"):
        drill.kill_set("host-row", 8, (8,))


def test_links_for_cuts_exactly_the_dead():
    links = drill.links_for(4, {2})
    for i in range(4):
        for j in range(4):
            expect = i != j and 2 not in (i, j)
            assert links[i][j] is expect


def test_agreement_connection_cost_model():
    # live coordinator: one dial per non-coordinator survivor
    assert drill.agreement_connections(64, {7}, "coordinator") == 62
    # dead coordinator: failed probes + the gossip fallback
    dead0 = drill.agreement_connections(8, {0}, "coordinator")
    gossip = drill.agreement_connections(8, {0}, "gossip")
    assert dead0 == 7 + gossip
    assert gossip == 2 * 7 * 7
    with pytest.raises(ValueError):
        drill.agreement_connections(8, (), "quorum")


# ---------------------------------------------------------------------------
# the drill matrix (8/16 in tier-1; 64 on the slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pattern", drill.PATTERNS)
@pytest.mark.parametrize("k", [8, 16])
def test_drill_patterns_pass_at_small_scale(pattern, k):
    m = drill.run_drill(pattern, k)
    assert m["recovered"] is True
    assert m["killed"]
    # O(k) star whenever the coordinator survived the first wave
    if pattern != "coordinator":
        assert m["agreement"]["coordinator_connections"] <= k
    if pattern == "host-row":
        assert m["neighbor_unrecoverable"] is True
    if pattern == "double":
        assert m["epochs"] == 2
        assert m["wave2"]["coordinator_connections"] <= m["wave2"]["k"]


@pytest.mark.slow
@pytest.mark.parametrize("pattern", drill.PATTERNS)
def test_drill_matrix_at_64_ranks(pattern):
    m = drill.run_drill(pattern, 64)
    assert m["recovered"] is True
    if pattern != "coordinator":
        assert m["agreement"]["coordinator_connections"] <= 64
        # the O(k) vs O(k^2) contrast the PR exists for (a dead
        # coordinator deliberately pays probes + the gossip fallback)
        assert m["agreement"]["gossip_connections"] \
            > 50 * max(1, m["agreement"]["coordinator_connections"])


def test_host_row_acceptance_2x4_and_4x2():
    """The acceptance criterion verbatim: host-row kill at 2x4 and 4x2
    restores every shard with the stripe and assertedly fails under the
    old neighbor placement."""
    for counts in ((4, 4), (2, 2, 2, 2)):
        m = drill.run_drill("host-row", sum(counts), counts=counts)
        assert m["recovered"] is True
        assert m["neighbor_unrecoverable"] is True
        # and the same kill under neighbor placement cannot even plan
        host_of = [h for h, c in enumerate(counts) for _ in range(c)]
        row = {r for r in range(sum(counts)) if host_of[r] == 1}
        with pytest.raises(el.RankFailure, match="unrecoverable"):
            el.plan_from_placement(
                row, el.neighbor_placement(sum(counts), 1))


def test_drill_asserts_when_restore_would_be_impossible():
    # running the host-row drill UNDER neighbor placement must fail
    # loudly (the harness refuses to report a drill it cannot restore)
    with pytest.raises(el.RankFailure, match="unrecoverable"):
        drill.run_drill("host-row", 8, placement="neighbor")


def test_drill_matrix_is_deterministic():
    a = drill.drill_matrix(ks=(8,), patterns=("single", "host-row"))
    b = drill.drill_matrix(ks=(8,), patterns=("single", "host-row"))
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


# ---------------------------------------------------------------------------
# the committed artifact
# ---------------------------------------------------------------------------


def test_bench_elastic_committed_payload_invariants():
    payload = json.loads((REPO / "BENCH_elastic.json").read_text())
    assert payload["schema"] == "mpx-elastic-drill/1"
    ks = [row["k"] for row in payload["per_k"]]
    assert ks == [8, 16, 64]
    for row in payload["per_k"]:
        # O(k) connections, against the O(k^2) gossip baseline
        assert row["coordinator_connections_max"] <= row["k"]
        assert row["gossip_connections"] >= row["k"] * (row["k"] - 1)
    proof = {p["topology"]: p for p in payload["host_row_proof"]}
    assert set(proof) == {"2x4", "4x2"}
    for p in proof.values():
        assert p["stripe_recovered"] and p["neighbor_unrecoverable"]
    # per-survivor repair bytes stay ~flat (here: strictly non-growing)
    per_rank = [row["repair_bytes_per_survivor_single"]
                for row in payload["per_k"]]
    assert per_rank == sorted(per_rank, reverse=True)


@pytest.mark.slow
def test_bench_elastic_reproduces_byte_identically(tmp_path):
    """CI's committed-artifact gate, as a test: regenerating the drill
    payload must reproduce the committed BENCH_elastic.json exactly."""
    out = tmp_path / "BENCH_elastic.json"
    subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "elastic_drill.py"),
         "--out", str(out)],
        check=True, cwd=str(REPO), timeout=300)
    assert out.read_text() == (REPO / "BENCH_elastic.json").read_text()
