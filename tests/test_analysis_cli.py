"""``python -m mpi4jax_tpu.analysis`` CLI: the exit-code contract.

Subprocess tests pinning all three exit codes (docs/analysis.md):

- 0 — scripts analyzed, no error-severity finding;
- 1 — at least one error-severity finding (a clean JSON payload with
  the findings is still printed under ``--json``);
- 2 — usage error / a script failing outside the verifier.

Plus the ``--json`` payload shape (scripts' own stdout is redirected to
stderr so the payload owns stdout) and ``--ranks`` plumbing into
``MPI4JAX_TPU_ANALYZE_RANKS``.
"""

import json
import os
import subprocess
import sys

import pytest

from envcheck import jax_meets_package_floor, subprocess_import_skip_reason

pytestmark = pytest.mark.skipif(
    not jax_meets_package_floor(), reason=subprocess_import_skip_reason()
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(tmp_path, script_body, *flags, name="script.py"):
    path = tmp_path / name
    path.write_text(script_body)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("MPI4JAX_TPU_ANALYZE", None)
    env.setdefault(
        "XLA_FLAGS",
        "--xla_force_host_platform_device_count=8")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis", *flags, str(path)],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )


_CLEAN = """
import jax
import mpi4jax_tpu as mpx

mesh = mpx.make_world_mesh(devices=jax.devices())
comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)

@mpx.spmd(comm=comm)
def step(x):
    out, _ = mpx.allreduce(x, comm=comm)
    return mpx.varying(out)

import jax.numpy as jnp
x = jnp.stack([jnp.full((8,), float(r)) for r in range(comm.Get_size())])
print("ran:", step(x).shape)
"""

_DIRTY = """
import jax
import jax.numpy as jnp
import mpi4jax_tpu as mpx

mesh = mpx.make_world_mesh(devices=jax.devices())
comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)

@mpx.spmd(comm=comm)
def step(x):
    t = mpx.create_token()
    a, t1 = mpx.allreduce(x, token=t, comm=comm)
    b, t2 = mpx.allreduce(x * 2, token=t, comm=comm)  # forked token
    return mpx.varying(a + b)

x = jnp.stack([jnp.full((8,), float(r)) for r in range(comm.Get_size())])
step(x)
"""

_BROKEN = """
raise ImportError("this script cannot even start")
"""


def test_exit_0_on_clean_script(tmp_path):
    res = _run_cli(tmp_path, _CLEAN, "--ranks", "8")
    assert res.returncode == 0, res.stderr[-3000:]
    assert "no errors" in res.stderr


def test_exit_1_on_error_finding_with_json(tmp_path):
    res = _run_cli(tmp_path, _DIRTY, "--ranks", "8", "--json")
    assert res.returncode == 1, res.stderr[-3000:]
    payload = json.loads(res.stdout)  # script prints went to stderr
    assert payload["errors"] >= 1
    findings = [f for rep in payload["reports"] for f in rep["findings"]]
    assert any(f["code"] == "MPX107" for f in findings)
    assert all({"code", "severity", "message", "op", "index", "rank",
                "seq"} <= set(f) for f in findings)


def test_exit_1_on_seeded_crossrank_deadlock():
    # the seeded rank-divergent deadlock example must FAIL the CLI with
    # MPX121 in the payload (the CI lane asserts the same)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("MPI4JAX_TPU_ANALYZE", None)
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis", "--ranks", "8",
         "--json", "examples/broken/rank_divergent_deadlock.py"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 1, res.stderr[-3000:]
    payload = json.loads(res.stdout)
    codes = {f["code"] for rep in payload["reports"]
             for f in rep["findings"]}
    assert "MPX121" in codes


def test_sys_exit_does_not_bypass_exit_code_contract(tmp_path):
    # a script ending in sys.exit(0) must not launder away its error
    # findings: the CLI's contract decides the process exit
    res = _run_cli(tmp_path, _DIRTY + "\nimport sys\nsys.exit(0)\n",
                   "--json")
    assert res.returncode == 1, res.stderr[-3000:]
    payload = json.loads(res.stdout)
    assert payload["errors"] >= 1


def test_exit_2_on_trace_failure(tmp_path):
    res = _run_cli(tmp_path, _BROKEN)
    assert res.returncode == 2, res.stderr[-3000:]
    assert "ImportError" in res.stderr


def test_exit_2_on_usage_error(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis"],  # no scripts
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert res.returncode == 2
    assert "usage:" in res.stderr
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis", "--bogus", "x.py"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert res.returncode == 2
