"""Cross-rank schedule verifier: the traced integration half.

Real 8-device programs through both front-ends (docs/analysis.md
"Cross-rank verification"):

- ``mpx.analyze(fn, *args, ranks='all')`` — per-rank re-trace with
  ``comm.Get_rank`` concretized, global matching, progress checking;
- the ambient ``MPI4JAX_TPU_ANALYZE=error`` path — the same pass at
  spmd trace time, before anything compiles.

Includes the seeded rank-divergent ``lax.cond`` deadlock
(examples/broken/rank_divergent_deadlock.py drives the same program),
a cross-host hierarchical program under a faked 2x4 topology, clean
full-scale programs (halo rings, split comms, fusion, start/wait), and
the HLO byte-identity pin with the cross-rank pass armed.  The pure
matcher/progress matrix lives in tests/test_crossrank_pure.py.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.analysis import crossrank, schedule
from helpers import ranks_arange, world

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples", "broken"))


@pytest.fixture(autouse=True)
def _reset_analysis(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_RANKS", raising=False)
    yield
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


def codes(report):
    return [f.code for f in report.findings]


# ---------------------------------------------------------------------------
# rank concretization through the real Comm
# ---------------------------------------------------------------------------


def test_get_rank_concretizes_inside_scope():
    comm, size = world()
    with schedule.scope(comm.axes, [size], 3):
        assert comm.Get_rank() == 3
        assert comm.global_rank() == 3
    # and returns to traced behavior outside
    assert not schedule.concretizing()


def test_group_comm_rank_concretizes():
    comm, size = world()
    split = comm.Split([r % 2 for r in range(size)])
    with schedule.scope(comm.axes, [size], 5):
        assert split.Get_rank() == 2  # rank 5 is the 3rd odd rank
        assert split.global_rank() == 5


# ---------------------------------------------------------------------------
# the seeded rank-divergent cond deadlock (both front-ends)
# ---------------------------------------------------------------------------


def _divergent_exchange(comm):
    from rank_divergent_deadlock import build_exchange

    return build_exchange(comm)


def test_seeded_deadlock_flagged_mpx121_by_analyze():
    comm, size = world()
    exchange = _divergent_exchange(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(exchange, x, comm=comm, ranks="all")
    assert "MPX121" in codes(report)
    cycles = [f for f in report.findings if f.code == "MPX121"]
    # one 2-rank cycle per even/odd pair
    assert len(cycles) == size // 2
    f = min(cycles, key=lambda f: f.rank)
    # the cycle is rendered rank-by-rank
    assert "rank 0: blocked at recv" in f.message
    assert "waits for rank 1" in f.message
    assert f.severity == "error"
    assert report.meta["ranks"] == list(range(size))


def test_seeded_deadlock_flagged_by_env_error_path():
    comm, _ = world()
    exchange = _divergent_exchange(comm)
    x = ranks_arange((16,))
    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError) as ei:
        mpx.run(exchange, x, comm=comm)
    assert any(f.code == "MPX121" for f in ei.value.findings)


def test_env_warn_path_warns_not_raises():
    comm, _ = world()
    exchange = _divergent_exchange(comm)
    x = ranks_arange((16,))
    mpx.set_analyze_mode("warn")
    with pytest.warns(UserWarning, match="MPX121"):
        # the cross-rank pass warns at trace time; the normal trace then
        # raises MPX102 (the divergent cond's recv has no queued send in
        # the single-program model) — both behaviors are the contract
        with pytest.raises(RuntimeError, match="MPX102"):
            mpx.run(exchange, x, comm=comm)


def test_env_ranks_off_disables_ambient_pass(monkeypatch):
    comm, _ = world()
    exchange = _divergent_exchange(comm)
    x = ranks_arange((16,))
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "off")
    mpx.set_analyze_mode("error")
    # without the cross-rank pass the divergent cond surfaces as the
    # single-trace MPX102 instead
    with pytest.raises(RuntimeError, match="MPX102"):
        mpx.run(exchange, x, comm=comm)


def test_env_ranks_cap_gates_by_world(monkeypatch):
    comm, size = world()
    exchange = _divergent_exchange(comm)
    x = ranks_arange((16,))
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", str(size - 1))
    mpx.set_analyze_mode("error")
    with pytest.raises(RuntimeError, match="MPX102"):  # capped out
        mpx.run(exchange, x, comm=comm)
    mpx.clear_caches()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", str(size))
    with pytest.raises(mpx.AnalysisError):  # within the cap
        mpx.run(exchange, x, comm=comm)


# ---------------------------------------------------------------------------
# divergent collective orders (MPX120 / MPX123) through analyze(ranks=)
# ---------------------------------------------------------------------------


def test_divergent_collective_interleave_mpx120():
    from jax import lax

    comm, _ = world()
    sub = comm.Clone()

    def step(x):
        r = comm.Get_rank()

        def even(v):
            a, t = mpx.allreduce(v, comm=comm)
            b, _ = mpx.allreduce(a, comm=sub, token=t)
            return b

        def odd(v):
            a, t = mpx.allreduce(v, comm=sub)
            b, _ = mpx.allreduce(a, comm=comm, token=t)
            return b

        return lax.cond(r % 2 == 0, even, odd, x)

    report = mpx.analyze(step, ranks_arange((8,)), comm=comm, ranks="all")
    assert "MPX120" in codes(report)


def test_orphaned_rank_mpx123():
    from jax import lax

    comm, _ = world()

    def step(x):
        r = comm.Get_rank()

        def zero(v):
            return v * 2.0  # rank 0 skips the collective entirely

        def rest(v):
            out, _ = mpx.allreduce(v, comm=comm)
            return out

        return lax.cond(r == 0, zero, rest, x)

    report = mpx.analyze(step, ranks_arange((8,)), comm=comm, ranks="all")
    assert "MPX123" in codes(report)
    (f,) = [f for f in report.findings if f.code == "MPX123"]
    assert f.rank == 0


def test_rank_as_structure_stays_mpx104_under_ranks():
    # concretization must not LAUNDER the rank into a valid static root:
    # the per-rank re-trace refuses rank-as-structure exactly like the
    # traced-rank form (analysis/schedule.RankConcrete), instead of
    # reporting the divergent roots as MPX120
    comm, _ = world()

    def step(x):
        out, _ = mpx.bcast(x, comm.Get_rank(), comm=comm)
        return out

    report = mpx.analyze(step, ranks_arange((8,)), comm=comm, ranks="all")
    assert "MPX104" in codes(report)
    assert "MPX120" not in codes(report)
    # rank-DERIVED statics are fine: a Python branch on parity picking a
    # uniform static root is the supported idiom
    def ok(x):
        r = comm.Get_rank()
        root = 0 if r % 2 == 0 else 0  # derived, uniform
        out, _ = mpx.bcast(x, root, comm=comm)
        return out

    assert mpx.analyze(ok, ranks_arange((8,)), comm=comm, ranks="all").ok


def test_ranks_subset_and_int():
    comm, size = world()

    def step(x):
        out, _ = mpx.allreduce(x, comm=comm)
        return out

    x = ranks_arange((8,))
    assert mpx.analyze(step, x, comm=comm, ranks=size).ok
    assert mpx.analyze(step, x, comm=comm, ranks=[0, 1]).ok
    with pytest.raises(ValueError, match="out of range"):
        mpx.analyze(step, x, comm=comm, ranks=size + 1)
    with pytest.raises(ValueError, match="region-style"):
        mpx.analyze(step, x, comm=comm, ranks="all", wrap=False)


# ---------------------------------------------------------------------------
# clean full-scale programs stay clean
# ---------------------------------------------------------------------------


def test_clean_halo_ring_and_split_and_fusion():
    comm, size = world()
    half = comm.Split([r % 2 for r in range(size)])

    def step(x):
        # sendrecv halo ring (send-then-recv per rank: buffered-safe)
        halo, t = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm)
        # whole-comm then split-comm collectives, token-chained
        a, t = mpx.allreduce(x + halo, comm=comm, token=t)
        b, t = mpx.allreduce(a, comm=half, token=t)
        c, _ = mpx.bcast(b, 0, comm=comm, token=t)
        return c

    report = mpx.analyze(step, ranks_arange((16,)), comm=comm, ranks="all")
    assert report.ok, report.render()


def test_clean_start_wait_overlap():
    comm, _ = world()

    def step(x):
        h = mpx.allreduce_start(x, comm=comm)
        y = x * 3.0
        out, _ = mpx.allreduce_wait(h)
        return out + y

    report = mpx.analyze(step, ranks_arange((64,)), comm=comm, ranks="all")
    assert report.ok, report.render()


def test_cross_host_hier_program_clean(monkeypatch):
    # the hierarchical_demo-style program under a faked 2x4 pod: the
    # two-level plan must agree on every rank (no MPX125) and the
    # schedules must match clean
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", "2x4")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "hier")
    mpx.clear_caches()
    comm, size = world()

    def step(v, b):
        s, tok = mpx.allreduce(v, op=mpx.PROD)
        c, tok = mpx.bcast(b[0], root=1, token=tok)
        d, _ = mpx.reduce_scatter(b, op=mpx.SUM, token=tok)
        return mpx.varying(s), mpx.varying(c), mpx.varying(d)

    v = ranks_arange((4096,))
    b = jnp.stack([
        jnp.arange(size * 8, dtype=jnp.float32).reshape(size, 8) + r
        for r in range(size)
    ])
    report = mpx.analyze(step, v, b, comm=comm, ranks="all")
    assert report.ok, report.render()
    # the hier plan was actually recorded and agreed on
    hiers = {e.hier for e in report.events if e.op == "allreduce"}
    assert (2, 4) in hiers


def test_examples_style_program_through_env_error():
    comm, _ = world()
    mpx.set_analyze_mode("error")

    @mpx.spmd(comm=comm)
    def step(x):
        halo, t = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm)
        out, _ = mpx.allreduce(x + halo, comm=comm, token=t)
        return mpx.varying(out)

    out = step(ranks_arange((8,)))  # traces + runs clean
    assert np.asarray(out).shape[0] == world()[1]


# ---------------------------------------------------------------------------
# zero-cost + memo contracts
# ---------------------------------------------------------------------------


def _lowered_text(fn, *args):
    return jax.jit(fn).lower(*args).as_text()


def test_hlo_byte_identical_across_modes_with_crossrank():
    # the ambient cross-rank pass is pure host-side re-tracing: the
    # lowered HLO must stay byte-identical in off/warn/error (the
    # acceptance pin; the per-checker version lives in test_analysis.py)
    from mpi4jax_tpu.parallel.region import spmd

    comm, _ = world()
    x = ranks_arange((8,))
    texts = {}
    for mode in (None, "warn", "error"):
        mpx.set_analyze_mode(mode)
        mpx.clear_caches()
        twin = spmd(lambda v: mpx.varying(mpx.allreduce(v, comm=comm)[0]),
                    comm=comm, jit=False)
        texts[mode] = _lowered_text(twin, x)
    assert texts[None] == texts["warn"] == texts["error"]


def test_ambient_pass_memoized_per_program(monkeypatch):
    comm, _ = world()
    calls = {"n": 0}
    orig = crossrank._run_region_pass

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(crossrank, "_run_region_pass", counting)
    mpx.set_analyze_mode("warn")

    @mpx.spmd(comm=comm)
    def step(x):
        out, _ = mpx.allreduce(x, comm=comm)
        return mpx.varying(out)

    x = ranks_arange((8,))
    step(x)
    step(x)  # warm call: the avals-keyed memo answers, no new pass
    assert calls["n"] == 1
    step(ranks_arange((16,)))  # new shapes: jit retraces AND so do we
    assert calls["n"] == 2
    mpx.clear_caches()
    step(x)  # memo dropped: the pass re-runs even on a cached program
    assert calls["n"] == 3
