"""Token threading and ordering under control flow.

Ports the ordering guarantees of ref tests/experimental/test_notoken.py:
134-190 (collectives inside fori_loop / while_loop / cond / nested jit) and
the token-chain tests.  In the SPMD design, ordering inside control flow is
inherited from JAX tracing (collectives inside lax loops are part of one
program); these tests pin that behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as mpx
from helpers import ranks_arange, world


def test_collective_inside_fori_loop():
    _, size = world()

    @mpx.spmd
    def f(x):
        def body(i, carry):
            y, _ = mpx.sendrecv(carry, carry, dest=mpx.shift(1))
            return y

        return jax.lax.fori_loop(0, size, body, x)

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.arange(size))  # full circle


def test_collective_inside_while_loop():
    _, size = world()

    @mpx.spmd
    def f(x):
        def cond(carry):
            i, _ = carry
            return i < 3

        def body(carry):
            i, v = carry
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            # collective results are replicated-typed; loop carries need a
            # stable type, so re-type as rank-varying (see sharp-bits)
            return i + 1, mpx.varying(s / size)

        _, out = jax.lax.while_loop(cond, body, (0, x))
        return out

    x = ranks_arange((1,))
    out = np.asarray(f(x))
    mean = np.arange(size).mean()
    assert np.allclose(out, mean)


def test_collective_inside_while_cond():
    # ref tests/experimental/test_notoken.py:292-313
    # (test_while_loop_consistency): the loop PREDICATE itself contains
    # communication.  Under SPMD this is a natural fit — a collective's
    # replicated result is exactly the rank-uniform scalar a while_loop
    # predicate requires.
    _, size = world()

    @mpx.spmd
    def f(x):
        def cond(v):
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            return jnp.all(s < 10 * size)

        def body(v):
            y, _ = mpx.sendrecv(v, v, dest=mpx.shift(1))
            return mpx.varying(y + 1.0)

        return jax.lax.while_loop(cond, body, x)

    out = np.asarray(f(ranks_arange((1,))))
    # every iteration permutes (sum-preserving) then adds 1 per rank:
    # sum grows by `size` per iteration from size*(size-1)/2 until >= 10*size
    start = size * (size - 1) / 2
    iters = int(np.ceil((10 * size - start) / size))
    assert np.allclose(np.sort(out.ravel()), np.sort(np.arange(size) + iters))


def test_collective_inside_cond():
    # both branches contain the same collective type — rank-uniform pred
    _, size = world()

    @mpx.spmd
    def f(x, flag):
        def true_fn(v):
            y, _ = mpx.allreduce(v, op=mpx.SUM)
            return y

        def false_fn(v):
            y, _ = mpx.allreduce(v, op=mpx.MAX)
            return y

        return jax.lax.cond(flag[0] > 0, true_fn, false_fn, x)

    x = ranks_arange((1,))
    flag_on = jnp.ones((size, 1), jnp.int32)
    flag_off = jnp.zeros((size, 1), jnp.int32)
    assert np.allclose(np.asarray(f(x, flag_on)), size * (size - 1) / 2)
    assert np.allclose(np.asarray(f(x, flag_off)), size - 1)


def test_collective_inside_nested_jit():
    _, size = world()

    @mpx.spmd
    def f(x):
        @jax.jit
        def inner(v):
            y, _ = mpx.allreduce(v, op=mpx.SUM)
            return y

        return inner(x)

    out = np.asarray(f(ranks_arange((1,))))
    assert np.allclose(out, size * (size - 1) / 2)


def test_token_chain_orders_collectives():
    # the token chain must impose a data dependence between the two psums in
    # the compiled HLO (each op's input ties to the previous op's output)
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.create_token()
        a, token = mpx.allreduce(x, op=mpx.SUM, token=token)
        b, token = mpx.allreduce(x * 0 + 1, op=mpx.SUM, token=token)
        return a, b

    a, b = f(ranks_arange((1,)))
    assert np.allclose(np.asarray(a), size * (size - 1) / 2)
    assert np.allclose(np.asarray(b), size)


def test_create_token_compat_arg():
    # ref create_token(x) took an array argument; accept and ignore
    t = mpx.create_token(jnp.zeros(3))
    assert isinstance(t, mpx.Token)


def test_token_is_pytree():
    t = mpx.create_token()
    leaves, treedef = jax.tree.flatten(t)
    assert len(leaves) == 1
    t2 = jax.tree.unflatten(treedef, leaves)
    assert isinstance(t2, mpx.Token)


def test_flush():
    mpx.flush()  # must not raise / deadlock (ref test_common.py:91-115)
