"""Pipeline schedule compiler: the pure-Python half (docs/pipeline.md).

The per-rank micro-op programs and their activation-stash bounds, the
warmup/steady/cooldown phase split, the bubble-time formulas and
``best_schedule`` argmin, the schedule builder's async point-to-point
extension (``send_start``/``recv_start``/``p2p_wait`` roles, wildcard
FIFO adoption, span matching — including inside megastep loop bodies),
the MPX144 schedule-mispick critic, the upgraded MPX135 advisory text,
and the ``pipeline_microbatches``/``pipeline_virtual_stages`` knob
plumbing — all loaded under a private package name (the
tests/test_analysis_pure.py isolated loader) so everything here runs
even where the installed JAX is below the package's floor.  The traced
integration half — real 8-device rounds through ``mpx.pipeline`` —
lives in tests/test_pipeline.py.
"""

import importlib
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_pipeline_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "ops", "parallel", "autotune"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._fusion", "ops._algos",
                "ops._hierarchy", "analysis.report", "analysis.graph",
                "analysis.checkers", "analysis.schedule",
                "analysis.matcher", "analysis.progress",
                "analysis.costmodel", "analysis.cost",
                "parallel.topology", "parallel.pipeline",
                "autotune.schema"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = sys.modules[f"{_ISO_NAME}.utils.config"]
cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
cost = sys.modules[f"{_ISO_NAME}.analysis.cost"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
schedule = sys.modules[f"{_ISO_NAME}.analysis.schedule"]
matcher = sys.modules[f"{_ISO_NAME}.analysis.matcher"]
progress = sys.modules[f"{_ISO_NAME}.analysis.progress"]
pipe = sys.modules[f"{_ISO_NAME}.parallel.pipeline"]
schema = sys.modules[f"{_ISO_NAME}.autotune.schema"]

S = schedule.SchedOp
E = graph.CollectiveEvent
MODEL = cm.CostModel()


def verify(schedules):
    m = matcher.match_schedules(schedules)
    return [f.code for f in m.findings + progress.check_progress(m)]


def run(schedules, **kw):
    matched = matcher.match_schedules(schedules)
    assert not matched.findings, matched.findings
    return cost.run_cost_pass(matched, model=kw.pop("model", MODEL), **kw)


# ---------------------------------------------------------------------------
# schedule programs + the activation-stash bound
# ---------------------------------------------------------------------------


def test_gpipe_program_shape_and_stash():
    prog = pipe.rank_program("gpipe", 4, 8, rank=0)
    assert prog[:8] == tuple(("F", i, 0) for i in range(8))
    assert prog[8:] == tuple(("B", i, 0) for i in reversed(range(8)))
    # the synchronous flush stashes EVERY microbatch
    assert pipe.stash_depth(prog) == 8


@pytest.mark.parametrize("stages", [2, 4, 8])
@pytest.mark.parametrize("microbatches", [2, 4, 8, 16])
def test_1f1b_stash_bound_min_s_m(stages, microbatches):
    # the PipeDream-flush memory claim: 1F1B's early backwards cap the
    # worst rank's stash at min(S, M); gpipe pays M regardless
    plan_g = pipe.compile_phases("gpipe", stages, microbatches)
    plan_f = pipe.compile_phases("1f1b", stages, microbatches)
    assert plan_g.max_stash == microbatches
    assert plan_f.max_stash == min(stages, microbatches)
    # rank 0 fills the deepest pipe; later ranks never stash more
    assert plan_f.stash_by_rank[0] == plan_f.max_stash
    assert all(d <= plan_f.max_stash for d in plan_f.stash_by_rank)


def test_1f1b_program_alternates_after_warmup():
    prog = pipe.rank_program("1f1b", 4, 8, rank=0)
    # every F matched by a B, F count == M
    assert sum(1 for op, *_ in prog if op == "F") == 8
    assert sum(1 for op, *_ in prog if op == "B") == 8
    # after the warmup prefix the steady state is strict F/B alternation
    warmup = 3  # s - 1 - rank
    steady = prog[warmup:warmup + 2 * (8 - warmup)]
    assert all(op == ("F" if i % 2 == 0 else "B")
               for i, (op, *_) in enumerate(steady))


def test_interleaved_program_chunks_and_phases():
    plan = pipe.compile_phases("interleaved", 4, 8, virtual=2)
    # p = S*v virtual stages: fill is p-1 ticks, M+p-1 total
    assert (plan.ticks, plan.warmup) == (8 + 8 - 1, 7)
    assert plan.steady == 8 - 7 and plan.cooldown == plan.ticks - 7 - 1
    prog = pipe.rank_program("interleaved", 4, 8, rank=1, virtual=2)
    assert {c for _op, _i, c in prog} == {0, 1}
    assert sum(1 for op, *_ in prog if op == "F") == 16  # M * v
    # interleaving stashes less than gpipe's M*v, more than flat 1f1b
    assert pipe.stash_depth(prog) <= 16


def test_phase_split_accounting():
    plan = pipe.compile_phases("1f1b", 4, 8)
    assert (plan.warmup, plan.steady, plan.cooldown) == (3, 5, 3)
    assert plan.ticks == plan.warmup + plan.steady + plan.cooldown
    # M < P: no steady window at all (the 8-stage example's shape)
    plan = pipe.compile_phases("1f1b", 8, 4)
    assert plan.steady == 0 and plan.ticks == 11


def test_schedule_validation_errors():
    with pytest.raises(ValueError, match="unknown schedule"):
        pipe.compile_phases("ladder", 4, 8)
    with pytest.raises(ValueError, match="virtual >= 2"):
        pipe.compile_phases("interleaved", 4, 8, virtual=1)
    with pytest.raises(ValueError, match="only applies"):
        pipe.compile_phases("gpipe", 4, 8, virtual=2)
    with pytest.raises(ValueError, match="out of range"):
        pipe.rank_program("gpipe", 4, 8, rank=4)
    with pytest.raises(ValueError, match="never stashed"):
        pipe.stash_depth((("B", 0, 0),))


# ---------------------------------------------------------------------------
# microbatch splitting + the knob plumbing
# ---------------------------------------------------------------------------


class _Arr:
    def __init__(self, shape):
        self.shape = tuple(shape)
        self.dtype = types.SimpleNamespace(itemsize=4)

    def reshape(self, shape):
        return _Arr(shape)


def test_split_microbatches_explicit():
    out = pipe.split_microbatches(_Arr((32, 8)), 4)
    assert out.shape == (4, 8, 8)
    with pytest.raises(ValueError, match="cannot split"):
        pipe.split_microbatches(_Arr((32, 8)), 5)


def test_split_microbatches_env_knob(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_PIPELINE_MICROBATCHES", "8")
    assert pipe.split_microbatches(_Arr((32, 8))).shape == (8, 4, 8)
    monkeypatch.delenv("MPI4JAX_TPU_PIPELINE_MICROBATCHES")
    # unset -> no split
    assert pipe.split_microbatches(_Arr((32, 8))).shape == (1, 32, 8)


def test_pipeline_knobs_declared_and_tuned():
    # flags declared (the _getenv registry contract) with 0 = unset
    for flag in ("MPI4JAX_TPU_PIPELINE_MICROBATCHES",
                 "MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES"):
        assert flag in config.FLAGS and config.FLAGS[flag].default == 0
    assert config.pipeline_microbatches() == 0
    assert config.pipeline_virtual_stages() == 0
    # the mpx-tuning/1 knob names map onto exactly those flags
    assert schema.KNOB_FLAGS["pipeline_microbatches"] == \
        "MPI4JAX_TPU_PIPELINE_MICROBATCHES"
    assert schema.KNOB_FLAGS["pipeline_virtual_stages"] == \
        "MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES"
    tf = schema.TuningFile({"schema": "mpx-tuning/1",
                            "tuned": {"pipeline_microbatches": 16,
                                      "pipeline_virtual_stages": 2}})
    assert tf.knob("pipeline_microbatches") == 16
    assert tf.knob("pipeline_virtual_stages", payload_bytes=4096) == 2
    # tuned values are >= 1 (0 = unset exists only as the static default)
    with pytest.raises(ValueError, match="pipeline_microbatches"):
        schema.TuningFile({"schema": "mpx-tuning/1",
                           "tuned": {"pipeline_microbatches": 0}})


def test_pipeline_virtual_env_knob(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES", "3")
    assert config.pipeline_virtual_stages() == 3
    prog = pipe.PipelineProgram(lambda h, p: h, None, "interleaved",
                                None, None, True)
    assert prog._resolve_virtual("interleaved") == 3


# ---------------------------------------------------------------------------
# bubble-time formulas + best_schedule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("microbatches", [4, 8, 16])
@pytest.mark.parametrize("payload", [1 << 10, 1 << 20])
def test_wall_time_orderings(microbatches, payload):
    # the pinned chain: serialized ladder > gpipe > 1f1b, every payload
    c = MODEL.compute_us(2 * payload)
    t = {s: cm.pipeline_wall_us(s, 8, microbatches, payload, c, MODEL)
         for s in ("ladder", "gpipe", "1f1b")}
    assert t["ladder"] > t["gpipe"] > t["1f1b"] > 0


def test_bubble_fraction_bounds_and_ordering():
    c = MODEL.compute_us(2 << 20)
    for s in ("ladder", "gpipe", "1f1b"):
        b = cm.pipeline_bubble_fraction(s, 8, 8, 1 << 20, c, MODEL)
        assert 0.0 <= b < 1.0
    b_ladder = cm.pipeline_bubble_fraction("ladder", 8, 8, 1 << 20, c,
                                           MODEL)
    b_1f1b = cm.pipeline_bubble_fraction("1f1b", 8, 8, 1 << 20, c, MODEL)
    assert b_ladder > b_1f1b
    # more microbatches amortize the fill: the bubble shrinks
    b_more = cm.pipeline_bubble_fraction("1f1b", 8, 32, 1 << 20, c, MODEL)
    assert b_more < b_1f1b


def test_interleaved_shrinks_the_fill():
    # transfer-light regime: the v-times-shallower fill wins
    payload = 1 << 10
    c = 500.0  # compute-dominated stage
    flat = cm.pipeline_wall_us("1f1b", 8, 8, payload, c, MODEL)
    inter = cm.pipeline_wall_us("interleaved", 8, 8, payload, c, MODEL,
                                virtual=4)
    assert inter < flat


def test_best_schedule_candidates_and_argmin():
    c = MODEL.compute_us(2 << 20)
    best, times = cm.best_schedule(8, 8, 1 << 20, c, MODEL, virtual=1)
    # the ladder is never a candidate; flat programs never interleave
    assert set(times) == {"gpipe", "1f1b"}
    assert best == "1f1b"
    # a chunked program (virtual >= 2) can only express interleaved:
    # gpipe/1f1b would need its chunks composed back into one stage fn
    # per rank, so they are never default candidates there
    best_v, times_v = cm.best_schedule(8, 8, 1 << 10, 500.0, MODEL,
                                       virtual=4)
    assert set(times_v) == {"interleaved"}
    assert best_v == "interleaved"
    # the cross-shape comparison stays available via explicit candidates
    best_x, times_x = cm.best_schedule(
        8, 8, 1 << 10, 500.0, MODEL, virtual=4,
        candidates=("gpipe", "1f1b", "interleaved"))
    assert set(times_x) == {"gpipe", "1f1b", "interleaved"}
    assert best_x == "interleaved"  # transfer-light: the fill win
    with pytest.raises(ValueError):
        cm.pipeline_wall_us("wavefront", 8, 8, 1 << 20, c, MODEL)
    with pytest.raises(ValueError):
        cm.pipeline_wall_us("gpipe", 0, 8, 1 << 20, c, MODEL)


# ---------------------------------------------------------------------------
# the schedule builder's async p2p extension
# ---------------------------------------------------------------------------


def test_send_start_recv_start_wait_roles():
    events = [
        E(0, "send_start", comm_uid=1, tag=0, pairs=((0, 1),), span=10,
          shape=(4,), dtype="f32"),
        E(1, "recv_start", comm_uid=1, tag=0, pairs=((0, 1),), span=11,
          shape=(4,), dtype="f32"),
        E(2, "p2p_wait", comm_uid=1, span=11, tag=0),
        E(3, "p2p_wait", comm_uid=1, span=10, tag=0),
    ]
    s0 = schedule.build_schedule(events, rank=0, world=2)
    s1 = schedule.build_schedule(events, rank=1, world=2)
    # sender: the transfer is issued AT the start (buffered — never
    # blocks); its wait emits nothing
    assert [o.kind for o in s0] == ["send"]
    assert (s0[0].dst, s0[0].span) == (1, 10)
    # receiver: the block point is the WAIT, so the recv SchedOp lands
    # at the wait's position — the overlap window is everything between
    assert [o.kind for o in s1] == ["recv"]
    assert (s1[0].src, s1[0].tag, s1[0].span) == (0, 0, 11)
    assert s1[0].event_index == 2
    assert verify({0: s0, 1: s1}) == []


def test_recv_start_wildcard_adopts_send_routing():
    # recv_start(source=None) adopts the queued send_start's routing
    # FIFO per (comm, tag) — the PR 7 adoption rule, now on spans
    fan_in = ((1, 0), (2, 0), (3, 0))
    events = [
        E(0, "send_start", comm_uid=1, tag=0, pairs=fan_in, span=5,
          shape=(4,), dtype="f32"),
        E(1, "recv_start", comm_uid=1, tag=0, pairs=None, span=6,
          shape=(4,), dtype="f32"),
        E(2, "p2p_wait", comm_uid=1, span=6, tag=0),
        E(3, "p2p_wait", comm_uid=1, span=5, tag=0),
    ]
    scheds = {r: schedule.build_schedule(events, rank=r, world=4)
              for r in range(4)}
    assert [o.kind for o in scheds[0]] == ["recv"] * 3
    assert {o.src for o in scheds[0]} == {1, 2, 3}
    for r in (1, 2, 3):
        assert [o.kind for o in scheds[r]] == ["send"]
    assert verify(scheds) == []


def test_p2p_span_matching_inside_megastep_loops():
    # the 1F1B steady state: start/wait pairs INSIDE a megastep loop
    # body (loop/unroll stamped), two spans in flight per iteration —
    # the builder must match spans, not positions, and the pipeline
    # stamp on the wait event must land on the emitted recv SchedOp
    # the traced boundary shape: send_start over the ring, wildcard
    # recv_start adopting its routing, recv-side wait, send-side wait
    stamp = ("1f1b", 2, 8, 1, 4096)
    ring = ((0, 1), (1, 0))
    events = [
        E(0, "send_start", comm_uid=1, tag=0, pairs=ring, span=20,
          loop=3, unroll=5, shape=(4,), dtype="f32"),
        E(1, "recv_start", comm_uid=1, tag=0, pairs=None, span=21,
          loop=3, unroll=5, shape=(4,), dtype="f32"),
        E(2, "p2p_wait", comm_uid=1, span=21, tag=0, loop=3, unroll=5,
          extra={"pipeline": stamp}),
        E(3, "p2p_wait", comm_uid=1, span=20, tag=0, loop=3, unroll=5),
    ]
    s0 = schedule.build_schedule(events, rank=0, world=2)
    s1 = schedule.build_schedule(events, rank=1, world=2)
    # every rank: buffered send at the start, recv at the WAIT position
    assert [o.kind for o in s0] == ["send", "recv"]
    assert [o.kind for o in s1] == ["send", "recv"]
    assert (s0[1].src, s1[1].src) == (1, 0)  # adopted ring routing
    # the recv emitted at the wait carries the wait event's stamp
    recv0 = s0[1]
    assert recv0.meta.get("pipeline") == stamp
    assert recv0.span == 21 and recv0.event_index == 2
    assert verify({0: s0, 1: s1}) == []


def test_unpaired_wait_and_wildcard_span():
    # a wait whose span never started emits nothing (MPX112 owns the
    # diagnosis at trace time); a wildcard recv_start with no queued
    # send stays a blocking wildcard at its wait
    events = [
        E(0, "p2p_wait", comm_uid=1, span=99, tag=0),
        E(1, "recv_start", comm_uid=1, tag=4, pairs=None, span=7,
          shape=(4,), dtype="f32", eager=True),
        E(2, "p2p_wait", comm_uid=1, span=7, tag=4),
    ]
    s0 = schedule.build_schedule(events, rank=0, world=2)
    assert [o.kind for o in s0] == ["recv"]
    assert s0[0].src is None and s0[0].tag == 4


# ---------------------------------------------------------------------------
# MPX144 — the schedule-mispick critic
# ---------------------------------------------------------------------------


def _stamped_pair(stamp, nbytes=1 << 20):
    return {
        0: [S(rank=0, pos=0, kind="send", op="send_start", comm_key=0,
              src=0, dst=1, tag=0, payload_bytes=nbytes)],
        1: [S(rank=1, pos=0, kind="recv", op="p2p_wait", comm_key=0,
              src=0, dst=1, tag=0, payload_bytes=nbytes,
              meta={"pipeline": stamp})],
    }


def test_mpx144_fires_on_priced_worse_schedule():
    # gpipe at a shape where 1f1b is strictly cheaper
    _, findings = run(_stamped_pair(("gpipe", 8, 8, 1, 1 << 20)))
    f = [x for x in findings if x.code == "MPX144"]
    assert len(f) == 1
    assert "'gpipe'" in f[0].message and "'1f1b'" in f[0].message
    assert "bubble fraction" in f[0].message
    assert "schedule='auto'" in f[0].suggestion
    assert f[0].severity == "advisory"


def test_mpx144_negative_when_schedule_is_best():
    _, findings = run(_stamped_pair(("1f1b", 8, 8, 1, 1 << 20)))
    assert not [x for x in findings if x.code == "MPX144"]


def test_mpx144_dedupes_and_ignores_malformed():
    # the same stamp on many ops fires once; junk stamps never crash
    scheds = _stamped_pair(("gpipe", 8, 8, 1, 1 << 20))
    scheds[1].append(
        S(rank=1, pos=1, kind="recv", op="p2p_wait", comm_key=0, src=0,
          dst=1, tag=1, payload_bytes=64,
          meta={"pipeline": ("gpipe", 8, 8, 1, 1 << 20)}))
    scheds[0].append(
        S(rank=0, pos=1, kind="send", op="send_start", comm_key=0, src=0,
          dst=1, tag=1, payload_bytes=64,
          meta={"pipeline": ("junk",)}))
    _, findings = run(scheds)
    assert len([x for x in findings if x.code == "MPX144"]) == 1


def test_mpx144_tuned_provenance():
    model = cm.CostModel(tuned_stamp="cafe12345678")
    _, findings = run(_stamped_pair(("gpipe", 8, 8, 1, 1 << 20)),
                      model=model)
    f = [x for x in findings if x.code == "MPX144"]
    assert f and "tuned@cafe12345678" in f[0].message


def test_mpx144_in_catalog_and_cost_codes():
    rep = sys.modules[f"{_ISO_NAME}.analysis.report"]
    assert rep.CODES["MPX144"].severity == "advisory"
    assert "MPX144" in cost.COST_CODES


# ---------------------------------------------------------------------------
# MPX135 — the upgraded advisory text
# ---------------------------------------------------------------------------


def _ladder_schedules(ranks=4, nbytes=1 << 16):
    schedules = {r: [] for r in range(ranks)}
    for s in range(1, ranks):
        schedules[s - 1].append(
            S(rank=s - 1, pos=len(schedules[s - 1]), kind="send",
              op="send", comm_key=0, src=s - 1, dst=s, tag=s,
              payload_bytes=nbytes))
        schedules[s].append(
            S(rank=s, pos=len(schedules[s]), kind="recv", op="recv",
              comm_key=0, src=s - 1, dst=s, tag=s, payload_bytes=nbytes))
    return schedules


def test_mpx135_cites_bubble_and_recommends_pipeline():
    _, findings = run(_ladder_schedules(ranks=4))
    f = [x for x in findings if x.code == "MPX135"]
    assert len(f) == 1
    assert "bubble fraction" in f[0].message
    assert "mpx.pipeline" in f[0].suggestion
    assert "1F1B" in f[0].suggestion and "us/round" in f[0].suggestion


def test_mpx135_tuned_provenance():
    model = cm.CostModel(tuned_stamp="beef98765432")
    _, findings = run(_ladder_schedules(ranks=4), model=model)
    f = [x for x in findings if x.code == "MPX135"]
    assert f and "tuned@beef98765432" in f[0].message


# ---------------------------------------------------------------------------
# the program's pure planning half
# ---------------------------------------------------------------------------


def test_program_plan_auto_resolves_via_cost_model():
    prog = pipe.pipeline(lambda h, p: h, 8)
    plan = prog.plan(8, 8, 1 << 20)
    assert plan.schedule == "1f1b"  # the model's pick at this shape
    assert plan.virtual == 1
    stamp = prog._stamp(plan, 1 << 20)
    assert stamp == ("1f1b", 8, 8, 1, 1 << 20)


def test_program_explicit_schedule_and_chunked_fns():
    prog = pipe.pipeline([lambda h, p: h, lambda h, p: h], 8,
                         schedule="interleaved")
    plan = prog.plan(4, 8, 4096)
    assert plan.schedule == "interleaved" and plan.virtual == 2
    with pytest.raises(ValueError, match="disagrees"):
        pipe.pipeline([lambda h, p: h], 8, schedule="interleaved",
                      virtual=3)
    with pytest.raises(ValueError, match="unknown schedule"):
        pipe.pipeline(lambda h, p: h, 8, schedule="ladder")
    with pytest.raises(TypeError, match="stage_fns"):
        pipe.pipeline([], 8)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_chunked_program_rejects_non_interleaved_schedules(schedule):
    # gpipe/1f1b apply ONE stage fn per rank; running them over a
    # chunked program would silently drop chunks 1..v-1 (the plan
    # compiles with virtual=1, so _chunk_fn only ever applies chunk 0)
    two = [lambda h, p: h, lambda h, p: h]
    with pytest.raises(ValueError, match="stage-chunks"):
        pipe.pipeline(two, 8, schedule=schedule)
    with pytest.raises(ValueError, match="stage-chunks"):
        pipe.pipeline(lambda h, p: h, 8, schedule=schedule, virtual=2)


def test_chunked_program_auto_restricts_candidates_to_interleaved():
    # schedule='auto' on a chunked program only prices what the program
    # can express: interleaved wins by default at EVERY regime, even
    # transfer-heavy shapes where a flat 1f1b would price cheaper
    prog = pipe.pipeline([lambda h, p: h, lambda h, p: h], 8)
    for payload in (1 << 10, 1 << 20):
        plan = prog.plan(8, 8, payload)
        assert plan.schedule == "interleaved"
        assert plan.virtual == 2
