"""Native host-hooks tests (C++ XLA FFI library, csrc/host_hooks.cc).

Mirrors the reference's observability and fatal-path test strategy
(SURVEY.md §4): debug-log format asserted on captured output
(ref tests/collective_ops/test_common.py:118-144) and abort semantics
verified in a subprocess with a scrubbed environment
(ref test_common.py:13-88).  ``capfd`` is used (not ``capsys``) because the
log lines are written by C++ ``fprintf``.
"""

import os
import re
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu import native
from mpi4jax_tpu.utils import set_runtime_tracing


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not native.available():
        native.build(verbose=False)
    assert native.available()
    yield


@pytest.fixture
def tracing():
    set_runtime_tracing(True)
    yield
    set_runtime_tracing(False)


LINE_RE = re.compile(r"^r(\d+) \| ([0-9a-f]{8}) \| (MPI_\w+)(.*)$")
DONE_RE = re.compile(
    r"^r(\d+) \| ([0-9a-f]{8}) \| (MPI_\w+) done with code 0 \((\d\.\d\de[-+]\d\ds)\)$"
)


def test_runtime_trace_format(capfd, tracing):
    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    out = np.asarray(f(jnp.arange(8.0)[:, None]))
    assert (out == 28).all()
    err = capfd.readouterr().err
    begin_lines = [l for l in err.splitlines()
                   if LINE_RE.match(l) and "done" not in l]
    done_lines = [l for l in err.splitlines() if DONE_RE.match(l)]
    # every rank logs one begin and one completion line
    assert len(begin_lines) == 8, err
    assert len(done_lines) == 8, err
    ranks = sorted(int(DONE_RE.match(l).group(1)) for l in done_lines)
    assert ranks == list(range(8))
    assert all(DONE_RE.match(l).group(3) == "MPI_Allreduce" for l in done_lines)


def test_runtime_trace_pairs_share_call_id(capfd, tracing):
    @mpx.spmd
    def f(x):
        a, tok = mpx.allreduce(x, op=mpx.SUM)
        b, _ = mpx.sendrecv(a, a, dest=mpx.shift(1), token=tok)
        return b

    np.asarray(f(jnp.arange(8.0)[:, None]))  # sync before reading capture
    err = capfd.readouterr().err
    ids = {}
    for line in err.splitlines():
        m = LINE_RE.match(line)
        if m:
            ids.setdefault(m.group(3), set()).add(m.group(2))
    # one call site per op: a single shared 8-char id each
    assert len(ids["MPI_Allreduce"]) == 1
    assert len(ids["MPI_Sendrecv"]) == 1
    assert ids["MPI_Allreduce"] != ids["MPI_Sendrecv"]


def test_trace_off_is_silent(capfd):
    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    np.asarray(f(jnp.arange(8.0)[:, None]))  # sync before reading capture
    err = capfd.readouterr().err
    assert not any(LINE_RE.match(l) for l in err.splitlines())


def test_wallclock_monotonic_ordering():
    @jax.jit
    def f(x):
        t1 = native.wallclock(x)
        t2 = native.wallclock(t1)
        return t1, t2

    t1, t2 = f(jnp.ones(4))
    assert float(t2) >= float(t1) > 0


def test_abort_if_false_is_noop():
    @jax.jit
    def f(x):
        native.abort_if(jnp.any(jnp.isnan(x)), 0, "nan detected")
        return x * 2

    out = np.asarray(f(jnp.ones(4)))
    assert (out == 2).all()


def test_abort_if_kills_process():
    # fatal-path subprocess isolation (ref test_common.py:60-88: MPI_Abort on
    # send-to-nonexistent-rank must kill the process, asserted on stderr)
    script = textwrap.dedent("""
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from mpi4jax_tpu import native

        @jax.jit
        def f(x):
            native.abort_if(jnp.any(jnp.isnan(x)), 0, "nan detected in gradient")
            return x

        f(jnp.full(4, jnp.nan)).block_until_ready()
        print("SHOULD NOT REACH", flush=True)
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode != 0
    assert "FATAL: nan detected in gradient" in proc.stderr
    assert "SHOULD NOT REACH" not in proc.stdout
