"""Hierarchical collectives: the traced integration half (docs/topology.md).

The pure lockstep proofs live in tests/test_hierarchy.py; here the same
two-level lowerings run for real on the 8-device CPU mesh under a faked
multi-host topology (``MPI4JAX_TPU_TOPOLOGY`` — the same knob the CI
topology lane uses):

- forced two-level vs forced flat equality for the reduction family
  (enum ops, a non-commutative callable, bcast across roots,
  reduce_scatter, a color split spanning hosts);
- ``auto`` selection (hier above the ring crossover on multi-host,
  flat otherwise), non-uniform fallback, and the HLO pins: single-host /
  below-crossover programs are byte-identical with and without topology
  support, and the forced two-level program moves chunk-sized payloads
  only;
- toggle-retrace for both program caches (topology + DCN crossover in
  the cache keys);
- composition: fused buckets ride the hierarchy, start/wait pairs split
  the two levels across the gap;
- telemetry's per-link-class byte counters match the pinned models.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import per_rank, ranks_arange, world


@pytest.fixture(autouse=True)
def _clean_topology_env(monkeypatch):
    for flag in ("MPI4JAX_TPU_TOPOLOGY", "MPI4JAX_TPU_DCN_CROSSOVER_BYTES",
                 "MPI4JAX_TPU_COLLECTIVE_ALGO",
                 "MPI4JAX_TPU_RING_CROSSOVER_BYTES",
                 "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES"):
        monkeypatch.delenv(flag, raising=False)
    yield


def _two_hosts(monkeypatch):
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    return 2, size // 2


def _forced(monkeypatch, algo):
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)


# ---------------------------------------------------------------------------
# equivalence: two-level == flat on the same data
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("op,npred", [
    (mpx.SUM, np.add.reduce),
    (mpx.PROD, np.multiply.reduce),
    (mpx.MIN, np.minimum.reduce),
    (mpx.MAX, np.maximum.reduce),
    (mpx.BXOR, np.bitwise_xor.reduce),
    (mpx.LAND, np.logical_and.reduce),
])
def test_hier_allreduce_matches_flat(op, npred, monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    if op in (mpx.BXOR,):
        vals = np.arange(size * 5, dtype=np.int32).reshape(size, 5)
    elif op is mpx.LAND:
        vals = (np.arange(size * 5).reshape(size, 5) % 3 != 0)
    elif op is mpx.PROD:
        vals = 1.0 + np.arange(size * 5, dtype=np.float64).reshape(
            size, 5) % 3  # small integer factors: exact in f64
    else:
        vals = np.arange(size * 5, dtype=np.float64).reshape(size, 5)
    x = jnp.asarray(vals)
    outs = {}
    for algo in ("butterfly", "hier"):
        _forced(monkeypatch, algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=op)
            return res

        outs[algo] = np.asarray(f(x))
    # exact data: the two-level fold must agree with the flat fold
    # bit for bit, and both with numpy's ascending reduction
    assert np.array_equal(outs["hier"], outs["butterfly"])
    expected = npred(vals, axis=0)
    assert np.array_equal(outs["hier"][0], expected)


def test_hier_allreduce_callable_right_projection(monkeypatch):
    """Right-projection is associative, non-commutative, elementwise: the
    ascending group-rank fold must yield the LAST rank's value through
    the two-level (forced) path too."""
    _, size = world()
    _two_hosts(monkeypatch)
    _forced(monkeypatch, "hier")

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=lambda a, b: b)
        return res

    out = np.asarray(f(ranks_arange((5,))))
    assert np.allclose(out, size - 1), out


def test_hier_bcast_matches_flat_all_roots(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    x = per_rank(lambda r: np.arange(6, dtype=np.float32) + 10 * r)
    for root in range(size):
        outs = {}
        for algo in ("butterfly", "hier"):
            _forced(monkeypatch, algo)

            @mpx.spmd
            def f(xl):
                res, _ = mpx.bcast(xl, root)
                return res

            outs[algo] = np.asarray(f(x))
        assert np.array_equal(outs["hier"], outs["butterfly"]), root
        expected = np.arange(6, dtype=np.float32) + 10 * root
        for r in range(size):
            assert np.array_equal(outs["hier"][r], expected), (root, r)


def test_hier_reduce_scatter_matches_flat(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    x = per_rank(
        lambda r: np.arange(size * 3, dtype=np.float64).reshape(size, 3) + r
    )
    outs = {}
    for algo in ("butterfly", "hier"):
        _forced(monkeypatch, algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.reduce_scatter(xl, op=mpx.SUM)
            return res

        outs[algo] = np.asarray(f(x))
    assert np.array_equal(outs["hier"], outs["butterfly"])
    base = np.arange(size * 3, dtype=np.float64).reshape(size, 3)
    for r in range(size):
        expected = base[r] * size + sum(range(size))
        assert np.array_equal(outs["hier"][r], expected), r


def test_hier_on_color_split_spanning_hosts(monkeypatch):
    comm, size = world()
    _two_hosts(monkeypatch)
    r = size // 2
    # two groups, each spanning both hosts with contiguous blocks
    colors = [0] * (r // 2) + [1] * (r - r // 2)
    colors = colors + colors  # e.g. 8 ranks, 2x4: (0,0,1,1, 0,0,1,1)
    split = comm.Split(colors)
    vals = np.arange(size * 4, dtype=np.float64).reshape(size, 4)
    x = jnp.asarray(vals)
    outs = {}
    for algo in ("butterfly", "hier"):
        _forced(monkeypatch, algo)

        @mpx.spmd(comm=comm)
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.SUM, comm=split)
            return res

        outs[algo] = np.asarray(f(x))
    assert np.array_equal(outs["hier"], outs["butterfly"])
    for g in split.groups:
        expected = vals[list(g)].sum(axis=0)
        for m in g:
            assert np.array_equal(outs["hier"][m], expected), (g, m)


def test_nonuniform_topology_falls_back_to_flat(monkeypatch):
    """A 3/5 host split: the hierarchy is inexpressible, a forced hier
    falls back to the auto rules — never an error, same results."""
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"{size - 3},3")
    x = ranks_arange((4,))
    _forced(monkeypatch, "hier")

    @mpx.spmd
    def f(xl):
        res, _ = mpx.allreduce(xl, op=mpx.PROD)
        return res

    out = np.asarray(f(x))
    assert np.allclose(out, 0.0)  # rank 0 contributes 0 to the product
    report = mpx.analyze(f, x)
    (evt,) = report.events
    assert evt.algo in ("butterfly", "ring")  # flat fallback
    assert evt.hosts is None  # no plan -> nothing for MPX113 to advise


# ---------------------------------------------------------------------------
# selection + HLO pins
# ---------------------------------------------------------------------------


def _prod(x):
    res, _ = mpx.allreduce(x, op=mpx.PROD)
    return res


def test_auto_picks_hier_above_crossover_only(monkeypatch):
    _two_hosts(monkeypatch)
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1024")
    report = mpx.analyze(_prod, ranks_arange((1024,)))  # 4 KiB payload
    (evt,) = report.events
    assert evt.algo == "hier" and evt.hosts == 2
    report = mpx.analyze(_prod, ranks_arange((8,)))  # 32 B payload
    (evt,) = report.events
    assert evt.algo == "butterfly"


def _lowered_prod(x):
    @mpx.spmd
    def f(xl):
        res, _ = mpx.allreduce(xl, op=mpx.PROD)
        return res

    return jax.jit(f).lower(x).as_text()


def test_hlo_byte_identical_single_host_and_below_crossover(monkeypatch):
    """The zero-cost contract: with no topology, an explicit single-host
    topology, or a multi-host topology at a below-crossover payload,
    the lowered program is byte-identical — topology support changes
    nothing until the hierarchy actually engages."""
    _, size = world()
    x = jnp.ones((size, 64), jnp.float32)  # 256 B: far below crossover
    base = _lowered_prod(x)
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"1x{size}")
    assert _lowered_prod(x) == base
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    assert _lowered_prod(x) == base
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"{size - 3},3")
    assert _lowered_prod(x) == base


def test_hier_hlo_moves_chunks_only(monkeypatch):
    """The byte-volume pin for the two-level program: every
    CollectivePermute round (intra reduce-scatter, inter exchange, intra
    allgather) carries an intra-chunk-sized payload — the full payload
    never rides a permute round."""
    _, size = world()
    h, r = _two_hosts(monkeypatch)
    _forced(monkeypatch, "hier")
    nelem = 64 * r  # intra chunk = 64 elements
    x = jnp.ones((size, nelem), jnp.float32)
    lines = [ln for ln in _lowered_prod(x).splitlines()
             if "collective_permute" in ln]
    # (r-1) intra reduce-scatter + >=1 inter + (r-1) intra allgather
    assert len(lines) >= 2 * (r - 1) + 1, len(lines)
    assert any(f"tensor<{nelem // r}xf32>" in ln for ln in lines)
    for ln in lines:
        assert f"tensor<{nelem}xf32>" not in ln, ln


# ---------------------------------------------------------------------------
# toggle-retrace: topology knobs are in both program-cache keys
# ---------------------------------------------------------------------------


def test_topology_toggle_retraces_eager_program(monkeypatch):
    _, size = world()
    mpx.clear_caches()
    x = ranks_arange((4,))
    mpx.allreduce(x, op=mpx.PROD)
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    mpx.allreduce(x, op=mpx.PROD)          # new topology: must retrace
    monkeypatch.setenv("MPI4JAX_TPU_DCN_CROSSOVER_BYTES", "123")
    mpx.allreduce(x, op=mpx.PROD)          # new DCN crossover: retrace
    monkeypatch.delenv("MPI4JAX_TPU_TOPOLOGY")
    monkeypatch.delenv("MPI4JAX_TPU_DCN_CROSSOVER_BYTES")
    mpx.allreduce(x, op=mpx.PROD)          # back to the first program
    s = mpx.cache_stats()
    assert s["misses"] == 3 and s["hits"] == 1
    mpx.clear_caches()


def test_topology_toggle_retraces_spmd_program(monkeypatch):
    _, size = world()
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    try:

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.PROD)
            return res

        x = ranks_arange((4,))
        f(x)
        f(x)                                        # hit
        monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
        f(x)                                        # miss: retrace
        meters = mpx.telemetry.snapshot()["meters"]
        assert meters.get("spmd_cache.misses") == 2
        assert meters.get("spmd_cache.hits") == 1
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


# ---------------------------------------------------------------------------
# composition: fusion buckets and start/wait pairs ride the hierarchy
# ---------------------------------------------------------------------------


def test_fused_bucket_rides_hierarchy(monkeypatch):
    """Fusion + topology: the fused flat-buffer bucket flushes through
    the ordinary dispatch point, so N member allreduces become ONE
    two-level exchange (the algo meter counts a single hier
    selection)."""
    _, size = world()
    _two_hosts(monkeypatch)
    _forced(monkeypatch, "hier")
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    mpx.set_fusion_mode("auto")
    try:

        @mpx.spmd
        def f(a, b):
            ra = mpx.allreduce(a, op=mpx.SUM)[0]
            rb = mpx.allreduce(b, op=mpx.SUM)[0]
            return mpx.varying(ra * 1.0), mpx.varying(rb * 1.0)

        a = jnp.full((size, 8), 2.0, jnp.float32)
        b = jnp.full((size, 4), 3.0, jnp.float32)
        oa, ob = f(a, b)
        assert np.allclose(np.asarray(oa), 2.0 * size)
        assert np.allclose(np.asarray(ob), 3.0 * size)
        meters = mpx.telemetry.snapshot()["meters"]
        buckets = sum(v for k, v in meters.items()
                      if k.startswith("fusion.") and k.endswith(".buckets"))
        assert buckets == 1
        assert meters.get("algo.allreduce.hier") == 1  # one exchange
    finally:
        mpx.set_fusion_mode(None)
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


def test_start_wait_pair_splits_the_two_levels(monkeypatch):
    """allreduce_start runs intra reduce-scatter + the DCN exchange and
    allreduce_wait the intra allgather; reduce_scatter_start runs the
    whole two-level exchange with a reassembly-only wait.  Results must
    match the monolithic flat collective (odd payload exercises chunk
    padding)."""
    _, size = world()
    _two_hosts(monkeypatch)
    _forced(monkeypatch, "hier")
    vals = 1.0 + (np.arange(size * 513).reshape(size, 513) % 3)
    x = jnp.asarray(vals, jnp.float32)

    @mpx.spmd
    def split_ar(g):
        h, tok = mpx.allreduce_start(g, op=mpx.SUM)
        s, _ = mpx.allreduce_wait(h, token=tok)
        return mpx.varying(s)

    out = np.asarray(split_ar(x))
    expected = vals.sum(axis=0)
    assert np.allclose(out, expected)

    rs_vals = np.arange(size * size * 2, dtype=np.float32).reshape(
        size, size, 2)
    xr = jnp.asarray(rs_vals)

    @mpx.spmd
    def split_rs(g):
        h, tok = mpx.reduce_scatter_start(g, op=mpx.SUM)
        s, _ = mpx.reduce_scatter_wait(h, token=tok)
        return mpx.varying(s)

    out_rs = np.asarray(split_rs(xr))
    for r in range(size):
        assert np.allclose(out_rs[r], rs_vals[:, r].sum(axis=0)), r


# ---------------------------------------------------------------------------
# alltoall: the two-level exchange + its HLO/selection pins
# ---------------------------------------------------------------------------


def _a2a_vals(size, per=6):
    # vals[g][d] distinct per (source, destination): any misrouting in
    # the two-level composition flips a visible value
    return np.arange(size * size * per, dtype=np.float32).reshape(
        size, size, per)


def test_hier_alltoall_matches_flat(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    vals = _a2a_vals(size)
    x = jnp.asarray(vals)
    outs = {}
    for algo in ("butterfly", "hier"):  # butterfly = forced flat
        _forced(monkeypatch, algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.alltoall(xl)
            return mpx.varying(res)

        outs[algo] = np.asarray(f(x))
    # a fixed permutation: bit-identical across lowerings, and equal to
    # the transposed global array
    assert np.array_equal(outs["hier"], outs["butterfly"])
    assert np.array_equal(outs["hier"], vals.transpose(1, 0, 2))


def test_hier_alltoall_on_color_split_spanning_hosts(monkeypatch):
    _, size = world()
    if size < 4:
        pytest.skip("needs >= 4 ranks for a 2-group split")
    _two_hosts(monkeypatch)
    comm, _ = world()
    split = comm.Split([r % 2 for r in range(size)])
    g = size // 2
    vals = np.arange(size * g * 3, dtype=np.float32).reshape(size, g, 3)
    x = jnp.asarray(vals)
    outs = {}
    for algo in ("butterfly", "hier"):
        _forced(monkeypatch, algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.alltoall(xl, comm=split)
            return mpx.varying(res)

        outs[algo] = np.asarray(f(x))
    assert np.array_equal(outs["hier"], outs["butterfly"])
    # group semantics: out[j] = group-member j's row for my group index
    groups = ([r for r in range(size) if r % 2 == 0],
              [r for r in range(size) if r % 2 == 1])
    for members in groups:
        for pos, r in enumerate(members):
            for j, src in enumerate(members):
                assert np.array_equal(outs["hier"][r][j],
                                      vals[src][pos]), (r, j)


def test_auto_alltoall_picks_hier_above_crossover_only(monkeypatch):
    _two_hosts(monkeypatch)
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1024")

    def a2a(x):
        res, _ = mpx.alltoall(x)
        return res

    _, size = world()
    report = mpx.analyze(a2a, jnp.ones((size, size, 256), jnp.float32))
    (evt,) = report.events
    assert evt.algo == "hier" and evt.hosts == 2
    assert evt.hier == (2, size // 2)
    report = mpx.analyze(a2a, jnp.ones((size, size, 2), jnp.float32))
    (evt,) = report.events
    assert evt.algo == "native" and evt.hier is None


def _lowered_a2a(x):
    @mpx.spmd
    def f(xl):
        res, _ = mpx.alltoall(xl)
        return mpx.varying(res)

    return jax.jit(f).lower(x).as_text()


def test_alltoall_hlo_byte_identical_below_crossover(monkeypatch):
    """The zero-cost contract for the permutation family: single-host
    comms and below-crossover payloads lower to the SAME program with
    and without the topology/crossover knobs in play."""
    _, size = world()
    x = jnp.ones((size, size, 8), jnp.float32)  # 256 B: far below
    base = _lowered_a2a(x)
    assert "all-to-all" in base or "all_to_all" in base  # the native HLO
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"1x{size}")
    assert _lowered_a2a(x) == base
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    assert _lowered_a2a(x) == base  # below the crossover: flat unchanged
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"{size - 3},3")
    assert _lowered_a2a(x) == base  # non-uniform: flat is the only form


def test_alltoall_crossover_toggle_retraces_eager_program(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    mpx.clear_caches()
    x = jnp.asarray(_a2a_vals(size))
    mpx.alltoall(x)
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1")
    out, _ = mpx.alltoall(x)  # new crossover: must retrace (hier now)
    assert np.array_equal(np.asarray(out),
                          _a2a_vals(size).transpose(1, 0, 2))
    monkeypatch.delenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES")
    mpx.alltoall(x)  # back to the first program
    s = mpx.cache_stats()
    assert s["misses"] == 2 and s["hits"] == 1
    mpx.clear_caches()


# ---------------------------------------------------------------------------
# telemetry: the per-link-class byte counters match the pinned models
# ---------------------------------------------------------------------------


def test_telemetry_link_classes_match_models(monkeypatch):
    from mpi4jax_tpu.ops._algos import algorithm_bytes_per_rank
    from mpi4jax_tpu.ops._hierarchy import hier_link_bytes

    _, size = world()
    h, r = _two_hosts(monkeypatch)
    nelem = 256
    nbytes = nelem * 4
    x = jnp.ones((size, nelem), jnp.float32)

    def run(algo):
        _forced(monkeypatch, algo)
        mpx.telemetry.reset()

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.PROD)
            return res

        f(x)
        rows = {row["algo"]: row
                for row in mpx.telemetry.snapshot()["ops"].values()}
        return rows[algo]

    mpx.set_telemetry_mode("counters")
    try:
        row = run("hier")
        assert (row["intra_bytes"], row["inter_bytes"]) == \
            hier_link_bytes("allreduce", nbytes, h, r)
        # a flat algorithm on the same multi-host comm: every round gates
        # on DCN, so the whole volume lands on the inter class
        row = run("ring")
        assert row["intra_bytes"] == 0
        assert row["inter_bytes"] == \
            algorithm_bytes_per_rank("ring", nbytes, size)
        # single host: everything back on intra
        monkeypatch.delenv("MPI4JAX_TPU_TOPOLOGY")
        row = run("ring")
        assert row["inter_bytes"] == 0
        assert row["intra_bytes"] == \
            algorithm_bytes_per_rank("ring", nbytes, size)
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()
