"""Exit-path regression: in-flight collectives must not wedge shutdown.

Port of ref tests/collective_ops/test_common.py:91-115
(test_deadlock_on_exit): the reference registers an atexit
``jax.effects_barrier()`` flush so pending async MPI ops complete before
MPI_Finalize.  Here the analog hazard is JAX async dispatch holding
in-flight collectives at interpreter teardown; mpi4jax_tpu registers the
same flush (mpi4jax_tpu/__init__.py + utils/flush.py).  The subprocess
issues a chain of collectives and exits WITHOUT any explicit sync; a clean
zero exit within the timeout is the assertion.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from envcheck import jax_meets_package_floor, subprocess_import_skip_reason

# the subprocess imports mpi4jax_tpu; below the package's jax floor that
# import refuses by design (container-environment-only failure)
pytestmark = pytest.mark.skipif(
    not jax_meets_package_floor(), reason=subprocess_import_skip_reason()
)


def test_clean_exit_with_inflight_collectives():
    script = textwrap.dedent("""
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import mpi4jax_tpu as mpx

        import atexit
        from mpi4jax_tpu.utils.flush import flush
        # the package must have registered the flush handler at import
        # (ref _src/__init__.py:13-17); atexit offers no public introspection,
        # so re-registering and checking idempotence is not possible — instead
        # assert the symbol exists and rely on the in-flight exit below.
        assert callable(flush)

        @mpx.spmd
        def chained(x):
            t = None
            for _ in range(25):
                x, t = mpx.sendrecv(x, x, dest=mpx.shift(1), token=t)
                x, t = mpx.allreduce(x * (1.0 / 8.0), op=mpx.SUM, token=t)
                x = mpx.varying(x)
            return x

        # launch and DO NOT sync — exit with the work still in flight
        chained(jnp.ones((8, 256)))
        print("DISPATCHED", flush=True)
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr
    assert "DISPATCHED" in proc.stdout
