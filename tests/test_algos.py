"""Payload-aware collective algorithms (ops/_algos.py): simulator + selector.

The ring and van-de-Geijn lowerings keep ALL of their static structure —
chunk layout, ppermute pair construction, per-round chunk index formulas,
and the order-preserving accumulator update rules — in plain functions
that are polymorphic over Python ints and traced values.  This file drives
those SAME functions through a pure-Python lockstep simulator:

- symbolic string folds pin the EXACT combine order (ascending group
  rank, the deterministic non-commutative contract ``apply_allreduce``
  documents) — any mis-routed round or mis-ordered combine changes the
  string;
- numpy folds pin the semantics of all 10 ``Op``s through the ring
  reduce-scatter;
- a chunk-level vdg simulation pins the binomial-scatter pair
  construction (clamped slices, dropped padding subtrees) for every
  (group size, root), power of two or not.

The module is loaded under a private package name (``_load_isolated``,
mirroring tests/test_resilience.py) so these tests run even where the
installed JAX is below the package's hard floor and ``import
mpi4jax_tpu`` refuses; the traced integration half lives in
tests/test_allreduce.py / test_reduce_scatter.py / test_split.py.
"""

import importlib
import os
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_algos_iso"


def _load_isolated():
    """Load ops/_algos.py + utils/config.py under a private package name,
    bypassing ``mpi4jax_tpu/__init__.py`` (whose JAX-floor check refuses
    to import on old JAX) while preserving package context for the
    relative imports."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._algos"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
al = ISO.ops._algos
config = ISO.utils.config


@pytest.fixture(autouse=True)
def _clean_algo_env():
    saved = {
        k: os.environ.pop(k, None)
        for k in ("MPI4JAX_TPU_COLLECTIVE_ALGO",
                  "MPI4JAX_TPU_RING_CROSSOVER_BYTES")
    }
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _where(cond, a, b):
    """The simulator's ``where``: a plain Python select (the traced
    appliers pass ``jnp.where`` into the same update rules)."""
    return a if cond else b


def _recv_map(k):
    """position -> predecessor, derived from the REAL ring pair table."""
    pairs = al.ring_pairs([tuple(range(k))])
    recv_from = {dst: src for src, dst in pairs}
    assert len(recv_from) == k  # every position receives exactly once
    return recv_from


def sim_ring_reduce_scatter(blocks, fn, k, preserve):
    """Pure-Python lockstep of ``apply_ring_reduce_scatter``: ``blocks[p][c]``
    is position ``p``'s block addressed to position ``c``; returns
    ``final[p]`` — the reduction position ``p`` ends up owning."""
    recv_from = _recv_map(k)
    if preserve:
        lo = [blocks[p][(p - 1) % k] for p in range(k)]
        hi = list(lo)
        for r in range(k - 1):
            rlo = [lo[recv_from[p]] for p in range(k)]
            rhi = [hi[recv_from[p]] for p in range(k)]
            nxt = [
                al.rs_update_pair(_where, fn, p, al.rs_recv_chunk(p, r, k),
                                  k, rlo[p], rhi[p],
                                  blocks[p][al.rs_recv_chunk(p, r, k)])
                for p in range(k)
            ]
            lo = [t[0] for t in nxt]
            hi = [t[1] for t in nxt]
        return [al.rs_finish_pair(_where, fn, p, k, lo[p], hi[p])
                for p in range(k)]
    acc = [blocks[p][(p - 1) % k] for p in range(k)]
    for r in range(k - 1):
        recvd = [acc[recv_from[p]] for p in range(k)]
        acc = [fn(recvd[p], blocks[p][al.rs_recv_chunk(p, r, k)])
               for p in range(k)]
    return acc


def sim_ring_allgather(vals, rel, k):
    """Lockstep of ``apply_ring_allgather``: position ``p`` contributes
    ``vals[p]`` as chunk ``rel[p]``; returns ``out[p][c]``."""
    recv_from = _recv_map(k)
    out = [[None] * k for _ in range(k)]
    cur = list(vals)
    for p in range(k):
        out[p][rel[p]] = vals[p]
    for r in range(k - 1):
        cur = [cur[recv_from[p]] for p in range(k)]
        for p in range(k):
            out[p][al.ag_recv_chunk(rel[p], r, k)] = cur[p]
    return out


# ---------------------------------------------------------------------------
# static structure
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,k", [(8, 4), (7, 4), (1, 8), (9, 8), (16, 1)])
def test_chunk_layout(n, k):
    chunk, padded = al.chunk_layout(n, k)
    assert padded == chunk * k
    assert padded >= n                      # payload always fits
    assert (chunk - 1) * k < n              # and the chunk is minimal


@pytest.mark.parametrize("k", [2, 3, 4, 7, 8])
def test_ring_pair_and_chunk_index_consistency(k):
    # the chunk a position receives is exactly what its ring predecessor
    # sends, every round; and after k-1 rounds each position's final
    # accumulator is its OWN chunk (reduce-scatter termination)
    for r in range(k - 1):
        for p in range(k):
            assert al.rs_recv_chunk(p, r, k) == al.rs_send_chunk((p - 1) % k, r, k)
    for p in range(k):
        assert al.rs_recv_chunk(p, k - 2, k) == p


def test_ring_pairs_skip_singletons():
    pairs = al.ring_pairs([(3,), (1, 5, 6)])
    assert pairs == [(1, 5), (5, 6), (6, 1)]


def test_next_pow2_and_vdg_widths():
    assert [al.next_pow2(k) for k in (1, 2, 3, 4, 5, 8, 9)] == \
        [1, 2, 4, 4, 8, 8, 16]
    assert al.vdg_widths(8) == [4, 2, 1]
    assert al.vdg_widths(1) == []


# ---------------------------------------------------------------------------
# ring reduce-scatter: exact combine order + all 10 op semantics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [2, 3, 4, 5, 8])
def test_ring_rs_preserves_ascending_fold_order(k):
    # string concatenation is associative and non-commutative with a fully
    # observable result: chunk c's fold must read (0:c)(1:c)...(k-1:c) —
    # the ascending group-rank order, exactly what apply_allreduce's
    # contract for associative non-commutative callables promises
    blocks = [[f"({p}:{c})" for c in range(k)] for p in range(k)]
    out = sim_ring_reduce_scatter(blocks, lambda a, b: a + b, k,
                                  preserve=True)
    for p in range(k):
        assert out[p] == "".join(f"({j}:{p})" for j in range(k))


@pytest.mark.parametrize("opname,npfn", [
    ("SUM", np.add), ("PROD", np.multiply), ("MIN", np.minimum),
    ("MAX", np.maximum), ("LAND", np.logical_and), ("LOR", np.logical_or),
    ("LXOR", np.logical_xor), ("BAND", np.bitwise_and),
    ("BOR", np.bitwise_or), ("BXOR", np.bitwise_xor),
])
@pytest.mark.parametrize("k", [2, 4, 8])
def test_ring_rs_all_ops(opname, npfn, k):
    # zlib.crc32, not hash(): string hashing is randomized per process
    # (PYTHONHASHSEED), so hash-seeded data made the float-association
    # slack of the rotated ring fold vs the ascending reference vary run
    # to run and occasionally exceed rtol (observed on SUM/k=8)
    import zlib

    rng = np.random.default_rng(zlib.crc32(f"{opname}/{k}".encode()))
    if opname in ("LAND", "LOR", "LXOR"):
        blocks = rng.integers(0, 2, size=(k, k, 3)).astype(bool)
    elif opname in ("BAND", "BOR", "BXOR"):
        blocks = rng.integers(0, 255, size=(k, k, 3)).astype(np.int32)
    else:
        blocks = rng.normal(size=(k, k, 3)).astype(np.float64)
    out = sim_ring_reduce_scatter(
        [[blocks[p, c] for c in range(k)] for p in range(k)],
        npfn, k, preserve=False)
    for p in range(k):
        expected = blocks[0, p]
        for j in range(1, k):
            expected = npfn(expected, blocks[j, p])
        np.testing.assert_allclose(np.asarray(out[p], dtype=np.float64),
                                   np.asarray(expected, dtype=np.float64),
                                   rtol=1e-12)


@pytest.mark.parametrize("k", [2, 3, 4, 8])
def test_ring_allgather_completeness(k):
    out = sim_ring_allgather([f"v{p}" for p in range(k)], list(range(k)), k)
    for p in range(k):
        assert out[p] == [f"v{c}" for c in range(k)]


# ---------------------------------------------------------------------------
# van de Geijn bcast: binomial scatter pair construction
# ---------------------------------------------------------------------------


def sim_vdg_bcast(k, root):
    """Chunk-level lockstep of ``apply_vdg_bcast`` over one uniform group:
    returns ``full[p]`` — the k real chunks position ``p`` reassembles."""
    K = al.next_pow2(k)
    groups = [tuple(range(k))]
    rel = [(p - root) % k for p in range(k)]
    # root holds the real payload ("R", c); everyone else garbage
    buf = [[("R", c) if p == root else ("G", p, c) for c in range(K)]
           for p in range(k)]
    for w in al.vdg_widths(K):
        pairs = al.vdg_scatter_pairs(groups, root, w, K)
        assert len(set(d for _, d in pairs)) == len(pairs)  # one sender each

        def slab(p):
            start = min(max(rel[p] + w, 0), K - w)  # dynamic_slice clamping
            return buf[p][start:start + w]

        recvd = {d: slab(s) for s, d in pairs}
        for p in range(k):
            if rel[p] % (2 * w) == w:
                # every real receiver position must have a sender pair —
                # a dropped pair here would leave it holding garbage
                assert p in recvd, (k, root, w, p)
                start = min(max(rel[p], 0), K - w)
                for i, v in enumerate(recvd[p]):
                    buf[p][start + i] = v
    mine = [buf[p][rel[p]] for p in range(k)]
    return sim_ring_allgather(mine, rel, k)


@pytest.mark.parametrize("k", [2, 3, 4, 5, 6, 7, 8, 9])
def test_vdg_bcast_delivers_root_payload(k):
    for root in range(k):
        full = sim_vdg_bcast(k, root)
        for p in range(k):
            assert full[p] == [("R", c) for c in range(k)], (k, root, p)


def test_vdg_scatter_pairs_drop_padding_subtrees():
    # k=5 -> K=8: receivers at relative positions >= 5 don't exist; their
    # whole subtrees carry only padding chunks and must be dropped
    groups = [tuple(range(5))]
    for w in al.vdg_widths(8):
        for _, dst in al.vdg_scatter_pairs(groups, 0, w, 8):
            assert dst < 5


# ---------------------------------------------------------------------------
# selector + byte-volume model
# ---------------------------------------------------------------------------


def test_resolve_algo_forced_and_fallback():
    big = config.DEFAULT_RING_CROSSOVER_BYTES * 4
    assert al.resolve_algo("butterfly", big, 8, True) == "butterfly"
    assert al.resolve_algo("ring", 1, 8, True) == "ring"
    # a forced ring falls back where the ring is not expressible
    assert al.resolve_algo("ring", big, 8, False) == "butterfly"


def test_resolve_algo_auto_crossover():
    cross = config.ring_crossover_bytes()
    assert al.resolve_algo("auto", cross - 1, 8, True) == "butterfly"
    assert al.resolve_algo("auto", cross, 8, True) == "ring"
    # tiny groups never ring under auto: 2·(k-1) rounds don't beat
    # 2·ceil(log2 k) and the byte volumes are comparable
    for k in range(2, al.RING_MIN_GROUP):
        assert al.resolve_algo("auto", cross * 64, k, True) == "butterfly"


def test_resolve_algo_env_crossover_override():
    os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"] = "256"
    assert al.resolve_algo("auto", 256, 8, True) == "ring"
    assert al.resolve_algo("auto", 255, 8, True) == "butterfly"


def test_algorithm_bytes_per_rank():
    # butterfly ships the full payload 2·ceil(log2 k) times; the ring ships
    # chunk-sized messages: (k-1)·chunk·2 (accumulator + allgather), one
    # more chunk stream for the order-preserving lo/hi pair
    assert al.algorithm_bytes_per_rank("butterfly", 1024, 8) == 2 * 3 * 1024
    assert al.algorithm_bytes_per_rank("ring", 1024, 8) == 7 * 128 * 2
    assert al.algorithm_bytes_per_rank("ring", 1024, 8, True) == 7 * 128 * 3
    assert al.algorithm_bytes_per_rank("ring", 1024, 1) == 0
    # the asymptotic claim of the whole layer: above k=4 the ring moves
    # strictly fewer bytes, and the gap grows with log k
    for k in (4, 8, 64, 1024):
        ring = al.algorithm_bytes_per_rank("ring", 1 << 20, k)
        fly = al.algorithm_bytes_per_rank("butterfly", 1 << 20, k)
        assert ring < fly
        assert ring <= 2 * (1 << 20)  # bandwidth-optimal bound 2·(k-1)/k·size


def test_ring_byte_count_matches_simulated_rounds():
    # count the messages the lockstep simulator actually ships: k-1
    # reduce-scatter rounds (pair-sized when order-preserving) + k-1
    # allgather rounds, one chunk each — the formula is not free-floating
    k, chunk_bytes = 8, 128
    for preserve, pair in ((False, 1), (True, 2)):
        shipped = (k - 1) * chunk_bytes * pair + (k - 1) * chunk_bytes
        assert shipped == al.algorithm_bytes_per_rank(
            "ring", chunk_bytes * k, k, preserve)


# ---------------------------------------------------------------------------
# config knobs + cache token
# ---------------------------------------------------------------------------


def test_collective_algo_default_and_validation():
    assert config.collective_algo() == "auto"
    os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = "RING"  # case-insensitive
    assert config.collective_algo() == "ring"
    os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = "doubling"
    with pytest.raises(ValueError, match="MPI4JAX_TPU_COLLECTIVE_ALGO"):
        config.collective_algo()


def test_ring_crossover_bytes_parsing():
    assert config.ring_crossover_bytes() == config.DEFAULT_RING_CROSSOVER_BYTES
    os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"] = "4096"
    assert config.ring_crossover_bytes() == 4096
    os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"] = "-1"
    with pytest.raises(ValueError, match="must be >= 0"):
        config.ring_crossover_bytes()
    os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"] = "1MB"
    with pytest.raises(ValueError, match="could not be parsed"):
        config.ring_crossover_bytes()


def test_algo_cache_token_reflects_every_knob():
    # mirrors tests/test_resilience.py::test_cache_token_reflects_every_knob:
    # each knob must change the compiled-program cache key, or toggling it
    # would silently keep serving the stale program
    base = al.algo_cache_token()
    tokens = {base}
    os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = "ring"
    tokens.add(al.algo_cache_token())
    os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"] = "123"
    tokens.add(al.algo_cache_token())
    assert len(tokens) == 3
    del os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"]
    del os.environ["MPI4JAX_TPU_RING_CROSSOVER_BYTES"]
    assert al.algo_cache_token() == base
