"""Arbitrary communicator color splits (MPI_Comm_split parity).

The reference marshals any mpi4py comm, including color splits
(ref mpi4jax/_src/utils.py:80-96); the grid form was already covered by
``comm.sub``.  This file pins the color form: ``comm.Split(colors, key)``
returns a GroupComm whose collectives are masked/gathered over the full
mesh axes (``axis_index_groups`` is unavailable under shard_map — verified
NotImplementedError on jax 0.9, see parallel/comm.py), correct for
non-Cartesian and unequal-sized groups.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.parallel.comm import GroupComm
from helpers import per_rank, ranks_arange, world

# the VERDICT-shaped example: a non-Cartesian, UNEQUAL 2-group partition
GROUPS_2 = ((0, 3, 5), (1, 2, 4, 6, 7))
COLORS_2 = [0, 1, 1, 0, 1, 0, 1, 1]
# a uniform non-Cartesian partition (evens/odds)
COLORS_EO = [r % 2 for r in range(8)]


def _expected_groupwise(vals, groups, fn):
    out = np.empty_like(np.asarray(vals))
    for g in groups:
        red = fn([vals[r] for r in g])
        for r in g:
            out[r] = red
    return out


def test_split_returns_groupcomm_with_mpi_ordering():
    comm, size = world()
    split = comm.Split(COLORS_2)
    assert isinstance(split, GroupComm)
    assert split.groups == GROUPS_2
    # key reorders within a group, ties broken by rank (MPI rule)
    keyed = comm.Split([0] * size, key=list(range(size))[::-1])
    assert keyed.groups == (tuple(range(size))[::-1],)


def test_split_allreduce_nonuniform_groups():
    comm, size = world()
    split = comm.Split(COLORS_2)

    @mpx.spmd
    def f(x):
        s, _ = mpx.allreduce(x, op=mpx.SUM, comm=split)
        m, _ = mpx.allreduce(x, op=mpx.MAX, comm=split)
        return s, m

    x = ranks_arange((2,))
    s, m = f(x)
    vals = np.arange(size, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0], _expected_groupwise(vals, GROUPS_2, sum))
    np.testing.assert_allclose(
        np.asarray(m)[:, 0], _expected_groupwise(vals, GROUPS_2, max))


def test_split_bcast_and_reduce_nonuniform():
    comm, size = world()
    split = comm.Split(COLORS_2)

    @mpx.spmd
    def f(x):
        b, t = mpx.bcast(x, 1, comm=split)  # group-local root 1
        r, _ = mpx.reduce(x, mpx.SUM, 0, comm=split, token=t)
        return b, r

    x = ranks_arange((1,))
    b, r = f(x)
    # bcast: every rank gets its group's local-rank-1 member's value
    exp_b = np.empty(size, np.float32)
    exp_r = np.arange(size, dtype=np.float32)  # non-root keeps input
    for g in GROUPS_2:
        exp_b[list(g)] = g[1]
        exp_r[g[0]] = sum(g)  # local root 0 gets the group sum
    np.testing.assert_allclose(np.asarray(b)[:, 0], exp_b)
    np.testing.assert_allclose(np.asarray(r)[:, 0], exp_r)


def test_split_rank_size_and_barrier():
    comm, size = world()
    split = comm.Split(COLORS_2)
    with pytest.raises(RuntimeError, match="unequal group sizes"):
        split.Get_size()
    uniform = comm.Split(COLORS_EO)
    assert uniform.Get_size() == size // 2

    @mpx.spmd
    def f(x):
        t = mpx.barrier(comm=split)
        r = split.Get_rank()
        return mpx.varying(jnp.asarray(r, jnp.float32))[None], t.value

    r, _ = f(ranks_arange((1,)))
    exp = np.empty(size, np.float32)
    for g in GROUPS_2:
        for i, rank in enumerate(g):
            exp[rank] = i
    np.testing.assert_allclose(np.asarray(r)[:, 0], exp)


def test_split_sendrecv_ring_within_groups():
    comm, size = world()
    split = comm.Split(COLORS_EO)

    @mpx.spmd
    def f(x):
        y, _ = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=split)
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    # each group is an independent ring: evens rotate among evens, odds
    # among odds
    exp = np.empty(size, np.float32)
    for g in ((0, 2, 4, 6), (1, 3, 5, 7)):
        for i, rank in enumerate(g):
            exp[g[(i + 1) % len(g)]] = rank
    np.testing.assert_allclose(out, exp)


def test_split_send_recv_and_status():
    comm, size = world()
    split = comm.Split(COLORS_EO)

    @mpx.spmd
    def f(x):
        s = mpx.Status()
        t = mpx.send(x, dest=mpx.shift(1), comm=split, tag=2)
        y, _ = mpx.recv(x, comm=split, tag=2, status=s, token=t)
        return y, s.Get_source()

    y, src = f(ranks_arange((1,)))
    n_loc = size // 2
    # Status.source is the GROUP-LOCAL rank of the sender (MPI semantics):
    # rank at local index i received from local index (i - 1) % n_loc
    exp_src = np.empty(size, np.int64)
    exp = np.empty(size, np.float32)
    for g in ((0, 2, 4, 6), (1, 3, 5, 7)):
        for i, rank in enumerate(g):
            exp_src[rank] = (i - 1) % n_loc
            exp[g[(i + 1) % len(g)]] = rank
    np.testing.assert_allclose(np.asarray(src), exp_src)
    np.testing.assert_allclose(np.asarray(y)[:, 0], exp)


def test_split_eager_allreduce():
    comm, size = world()
    split = comm.Split(COLORS_2)
    s, _ = mpx.allreduce(ranks_arange((1,)), op=mpx.SUM, comm=split)
    vals = np.arange(size, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0], _expected_groupwise(vals, GROUPS_2, sum))


def test_split_grad_through_group_allreduce():
    comm, size = world()
    split = comm.Split(COLORS_2)

    def loss(x):
        @mpx.spmd
        def f(xl):
            s, _ = mpx.allreduce(xl, op=mpx.SUM, comm=split)
            return jnp.sum(s ** 2)

        return jnp.sum(f(x))

    x = per_rank(lambda r: jnp.full((1,), float(r + 1)))
    g = np.asarray(jax.grad(loss)(x))[:, 0]
    # d/dx_r sum_ranks (group_sum)^2 = 2 * |group| * group_sum
    vals = np.arange(1, size + 1, dtype=np.float32)
    exp = np.empty(size, np.float32)
    for grp in GROUPS_2:
        s = sum(vals[r] for r in grp)
        for r in grp:
            exp[r] = 2 * len(grp) * s
    np.testing.assert_allclose(g, exp, rtol=1e-6)


def test_split_gather_family_uniform_groups():
    # on UNIFORM groups every op works: the gathered output shape
    # (group_size, *s) is the same on all ranks
    comm, size = world()
    split = comm.Split(COLORS_EO)
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))

    @mpx.spmd
    def f(x):
        ag, t = mpx.allgather(x, comm=split)
        g, t = mpx.gather(x, 1, comm=split, token=t)
        sc, t = mpx.scan(x, mpx.SUM, comm=split, token=t)
        return ag, g, sc

    ag, g, sc = f(ranks_arange((1,)))
    for grp in groups:
        for i, rank in enumerate(grp):
            np.testing.assert_allclose(np.asarray(ag)[rank, :, 0], grp)
            np.testing.assert_allclose(np.asarray(g)[rank, :, 0], grp)
            # inclusive prefix over group order
            np.testing.assert_allclose(
                np.asarray(sc)[rank, 0], sum(grp[: i + 1]))


def test_split_alltoall_and_scatter_uniform_groups():
    comm, size = world()
    split = comm.Split(COLORS_EO)
    gs = size // 2
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    # x[r, j] = 10*r + j: rank r's slice addressed to group-local index j
    x = per_rank(lambda r: 10.0 * r + np.arange(gs, dtype=np.float32))

    @mpx.spmd
    def f(x):
        a2a, t = mpx.alltoall(x, comm=split)
        sct, _ = mpx.scatter(x, 2, comm=split, token=t)  # group root 2
        return a2a, sct

    a2a, sct = f(x)
    for grp in groups:
        for i, rank in enumerate(grp):
            # alltoall: out[j] = member j's row i
            np.testing.assert_allclose(
                np.asarray(a2a)[rank, :, ], [10.0 * m + i for m in grp])
            # scatter from group-local root 2: out = root's row i
            np.testing.assert_allclose(
                np.asarray(sct)[rank], 10.0 * grp[2] + i)


def test_split_gather_family_nonuniform_raises():
    comm, _ = world()
    split = comm.Split(COLORS_2)
    with pytest.raises(RuntimeError, match="unequal group sizes"):
        mpx.allgather(ranks_arange((1,)), comm=split)


def test_split_p2p_nonuniform_groups():
    """Point-to-point on UNEQUAL groups: shift routing normalizes at each
    group's own size (a per-group ring), via the static member tables."""
    comm, size = world()
    split = comm.Split(COLORS_2)

    @mpx.spmd
    def ring(x):
        y, t = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=split)
        t2 = mpx.send(x, dest=mpx.shift(-1), tag=3, comm=split, token=t)
        z, _ = mpx.recv(x, source=mpx.shift(1), tag=3, comm=split, token=t2)
        return y, z

    y, z = ring(ranks_arange((1,)))
    exp_y = np.empty(size, np.float32)
    exp_z = np.empty(size, np.float32)
    for g in GROUPS_2:
        n = len(g)
        for i, r in enumerate(g):
            exp_y[r] = g[(i - 1) % n]  # received from group-left neighbor
            exp_z[r] = g[(i + 1) % n]  # send left <=> recv from group-right
    np.testing.assert_allclose(np.asarray(y)[:, 0], exp_y)
    np.testing.assert_allclose(np.asarray(z)[:, 0], exp_z)


def test_split_p2p_nonuniform_dict_raises():
    comm, _ = world()
    split = comm.Split(COLORS_2)
    with pytest.raises(ValueError, match="out of range"):
        # rank 3 exists in the 5-group but not the 3-group
        mpx.sendrecv(ranks_arange((1,)), ranks_arange((1,)),
                     dest={0: 3}, comm=split)


def test_split_scan_nonuniform_groups():
    """Prefix reduction on UNEQUAL groups: scan's routing comes from the
    static group tables (one masked permute round per doubling offset up
    to the largest group), so the uniform-size restriction of the
    shape-bound ops does not apply to it."""
    comm, size = world()
    split = comm.Split(COLORS_2)

    sc, _ = mpx.scan(ranks_arange((1,)), mpx.SUM, comm=split)
    out = np.asarray(sc)[:, 0]
    exp = np.empty(size, np.float32)
    for g in GROUPS_2:
        run = 0.0
        for r in g:
            run += r
            exp[r] = run  # inclusive prefix in group order
    np.testing.assert_allclose(out, exp)


def test_split_validation_errors():
    comm, size = world()
    with pytest.raises(ValueError, match="every rank's color"):
        comm.Split([0, 1])
    with pytest.raises(ValueError, match="one entry per rank"):
        comm.Split([0] * size, key=[0])
    split = comm.Split(COLORS_EO)
    # nested Split works (test_split_nested) but still wants the
    # world-length table, not a group-length one
    with pytest.raises(ValueError, match="GLOBAL rank"):
        split.Split([0] * (size // 2))
    with pytest.raises(ValueError, match="sub\\(\\) on a color-split"):
        split.sub("x")


def test_split_axis_string_form_unchanged():
    # the pre-existing Cartesian form must keep working
    mesh = mpx.make_world_mesh((2, 4), ("sy", "sx"))
    comm = mpx.Comm(("sy", "sx"), mesh=mesh)
    rows = comm.Split("sy")  # drop sy -> row comm over sx
    assert rows.axes == ("sx",)
    assert not isinstance(rows, GroupComm)


def test_split_clone_isolates_matching():
    comm, size = world()
    split = comm.Split(COLORS_EO)
    clone = split.Clone()
    assert isinstance(clone, GroupComm)
    assert clone.groups == split.groups
    assert clone.uid != split.uid


def test_split_eager_send_recv():
    comm, size = world()
    split = comm.Split(COLORS_EO)
    # eager global arrays span ALL ranks even on a color-split comm; the
    # routing spec is group-local
    x = ranks_arange((1,))
    t = mpx.send(x, dest=mpx.shift(1), comm=split, tag=5)
    y, _ = mpx.recv(x, comm=split, tag=5, token=t)
    exp = np.empty(size, np.float32)
    for g in ((0, 2, 4, 6), (1, 3, 5, 7)):
        for i, rank in enumerate(g):
            exp[g[(i + 1) % len(g)]] = rank
    np.testing.assert_allclose(np.asarray(y)[:, 0], exp)


def test_split_bind_preserves_groups():
    comm, size = world()
    split = comm.Split(COLORS_2)
    bound = split.bind(split.mesh)
    assert isinstance(bound, GroupComm)
    assert bound.groups == split.groups
    assert bound.uid == split.uid
    s, _ = mpx.allreduce(ranks_arange((1,)), op=mpx.SUM, comm=bound)
    vals = np.arange(size, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0], _expected_groupwise(vals, GROUPS_2, sum))


def test_split_allreduce_noncommutative_op_group_consistent():
    # a callable op need not be commutative (associativity is MPI's only
    # requirement — association order is the library's choice, rank order
    # is not); every member of a group must receive the SAME result: the
    # fold of the group's members in ascending group-rank order.  The 2x2
    # matrix product pins both properties.
    comm, size = world()
    split = comm.Split(COLORS_EO)

    @mpx.spmd
    def f(x):
        s, _ = mpx.allreduce(x, op=jnp.matmul, comm=split)
        return s

    rng = np.random.default_rng(1)
    mats = rng.normal(size=(size, 2, 2)).astype(np.float32)
    out = np.asarray(f(jnp.asarray(mats)))
    for g in ((0, 2, 4, 6), (1, 3, 5, 7)):
        expected = np.eye(2, dtype=np.float32)
        for r in g:
            expected = expected @ mats[r]
        for r in g:
            np.testing.assert_allclose(out[r], expected, rtol=1e-5,
                                       atol=1e-5)


def test_split_nested():
    """Nested MPI_Comm_split: refining a split refines WITHIN each group
    (world-length color table, group-local-rank tie-breaking)."""
    comm, size = world()
    parent = comm.Split(COLORS_2)  # (0,3,5) / (1,2,4,6,7)
    nested = parent.Split([r % 2 for r in range(size)])
    assert nested.groups == ((0,), (3, 5), (2, 4, 6), (1, 7))

    s, _ = mpx.allreduce(ranks_arange((1,)), mpx.SUM, comm=nested)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0],
        _expected_groupwise(np.arange(8.0), nested.groups, sum),
    )

    with pytest.raises(ValueError, match="grid splits"):
        parent.Split("py")
    with pytest.raises(ValueError, match="GLOBAL rank"):
        parent.Split([0, 1])


def test_split_eager_unequal_p2p_and_scan():
    """The standalone-eager path (cached one-op programs, resolve_routing
    at build time) handles unequal splits too."""
    comm, size = world()
    split = comm.Split(COLORS_2)
    x = ranks_arange((1,))

    ring, _ = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=split)
    sc, _ = mpx.scan(x, mpx.SUM, comm=split)
    exp_ring = np.empty(size, np.float32)
    exp_sc = np.empty(size, np.float32)
    for g in GROUPS_2:
        run = 0.0
        for i, r in enumerate(g):
            exp_ring[r] = g[(i - 1) % len(g)]
            run += r
            exp_sc[r] = run
    np.testing.assert_allclose(np.asarray(ring)[:, 0], exp_ring)
    np.testing.assert_allclose(np.asarray(sc)[:, 0], exp_sc)


def test_split_algo_equivalence_ring_vs_butterfly(monkeypatch):
    """The payload-aware layer on a color split: PROD (never native) must
    agree across auto, forced butterfly, and forced ring on uniform
    groups — with a payload not divisible by the group size, so the
    ring's chunk padding is exercised."""
    comm, size = world()
    split = comm.Split(COLORS_EO)
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    rng = np.random.default_rng(11)
    vals = rng.uniform(0.5, 1.5, size=(size, 5)).astype(np.float32)
    for algo in ("auto", "butterfly", "ring"):
        monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)

        @mpx.spmd
        def f(x):
            s, _ = mpx.allreduce(x, op=mpx.PROD, comm=split)
            return s

        out = np.asarray(f(jnp.asarray(vals)))
        for grp in groups:
            expected = np.prod([vals[r] for r in grp], axis=0)
            for r in grp:
                np.testing.assert_allclose(out[r], expected, rtol=1e-5,
                                           err_msg=f"algo={algo}")


def test_split_forced_ring_unequal_groups_falls_back(monkeypatch):
    """The ring lowerings need a uniform static group size (the chunk
    count); a forced ring on an UNEQUAL partition must fall back to the
    butterfly — still correct, never an error."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    comm, size = world()
    split = comm.Split(COLORS_2)
    s, _ = mpx.allreduce(ranks_arange((3,)), op=mpx.SUM, comm=split)
    vals = np.arange(size, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0], _expected_groupwise(vals, GROUPS_2, sum))
    b, _ = mpx.bcast(ranks_arange((1,)), 1, comm=split)
    exp_b = np.empty(size, np.float32)
    for g in GROUPS_2:
        exp_b[list(g)] = g[1]
    np.testing.assert_allclose(np.asarray(b)[:, 0], exp_b)


def test_split_bcast_vdg_ring(monkeypatch):
    """Forced-ring bcast on a uniform split takes the van de Geijn
    scatter + ring-allgather lowering; a payload not divisible by the
    group size exercises the virtual-chunk padding."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    comm, size = world()
    split = comm.Split(COLORS_EO)
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    x = per_rank(lambda r: 10.0 * r + np.arange(5, dtype=np.float32))

    @mpx.spmd
    def f(xl):
        b, _ = mpx.bcast(xl, 2, comm=split)
        return b

    out = np.asarray(f(x))
    for g in groups:
        for r in g:
            np.testing.assert_allclose(out[r], np.asarray(x)[g[2]])


def test_split_bcast_auto_crossover_picks_vdg(monkeypatch):
    """``auto`` routes large split-comm broadcasts to the vdg lowering
    once the payload crosses MPI4JAX_TPU_RING_CROSSOVER_BYTES — pinned by
    shrinking the crossover to 1 byte instead of shipping megabytes."""
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "1")
    comm, size = world()
    split = comm.Split(COLORS_EO)
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    x = per_rank(lambda r: float(r) + np.arange(8, dtype=np.float32))

    @mpx.spmd
    def f(xl):
        b, _ = mpx.bcast(xl, 0, comm=split)
        return b

    out = np.asarray(f(x))
    for g in groups:
        for r in g:
            np.testing.assert_allclose(out[r], np.asarray(x)[g[0]])


def test_split_integer_colors_order_numerically():
    """Integer colors order groups numerically (10 after 2), not
    lexicographically; string colors keep lexicographic order (advisor
    r4 finding: str() sorting surprised users with 10 < 2)."""
    comm, size = world()
    num = comm.Split([0, 10, 2, 10, 2, 0, 10, 2])
    assert num.groups == ((0, 5), (2, 4, 7), (1, 3, 6))
    nested = num.Split([10 if r % 2 else 2 for r in range(size)])
    # within each numeric-ordered parent group, color 2 precedes color 10
    assert nested.groups == (
        (0,), (5,), (2, 4), (7,), (6,), (1, 3),
    )
    txt = comm.Split(["b", "a", "b", "a", "a", "b", "a", "b"])
    assert txt.groups == ((1, 3, 4, 6), (0, 2, 5, 7))
