"""Runtime health plane tests (docs/observability.md "Runtime health").

Covers mpi4jax_tpu/telemetry/health.py and its integrations:

- the flight recorder: overwrite-ring semantics, the counters-tier
  dispatch feed, the events-tier begin/record spill from the journal,
  ``flight_snapshot()``/drop accounting, capacity changes;
- the degradation detector: window-vs-baseline local slowdown, the pure
  cross-rank ``judge_exchange`` verdicts, consecutive-strike promotion
  to *persistent*, interval gating at boundaries, and the opt-in
  suspect handoff into the elastic agreement machinery
  (``MPI4JAX_TPU_HEALTH_SUSPECTS``);
- postmortem bundles: write/overwrite with reason accumulation, the
  watchdog-expiry and rank-failure triggers, ``read_bundles`` /
  ``postmortem_report`` / ``render_postmortem`` and the ``postmortem``
  CLI (exit 0 with bundles and a named straggler, 2 without);
- dropped-record surfacing: the ``telemetry.dropped`` meter, the
  only-when-nonzero ``dropped`` snapshot key, the ``report()`` line,
  and the merge CLI warning;
- ``prometheus_text()`` exposition and gauges;
- the MPX143 ring-sizing advisory (pure checker + catalog sync);
- the off-is-free invariants: no ring, no snapshot key, unchanged
  cache token, no ``flight_ring`` in the verifier config snapshot.

Everything here is the pure half (isolated loader, no jax); the HLO
byte-identity pin for HEALTH=on/off and the multi-process drill live in
tests/test_telemetry.py's jax half and the CI faults lane.
"""

import importlib
import json
import os
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_health_iso"


def _load_isolated():
    """The telemetry + analysis + resilience stack under a private
    package name (tests/test_telemetry.py's loader, widened): bypasses
    the package __init__'s JAX floor and isolates module state."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "telemetry", "analysis", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "telemetry.hist",
        "telemetry.health",
        "telemetry.core",
        "telemetry.journal",
        "telemetry.merge",
        "telemetry.report",
        "analysis.graph",
        "analysis.report",
        "analysis.checkers",
        "analysis.hook",
        "resilience.faultinject",
        "resilience.retry",
        "resilience.watchdog",
        "resilience.elastic",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = ISO.utils.config
health = ISO.telemetry.health
core = ISO.telemetry.core
journal = ISO.telemetry.journal
merge = ISO.telemetry.merge
treport = ISO.telemetry.report
graphmod = ISO.analysis.graph
checkers = ISO.analysis.checkers
areport = ISO.analysis.report
hook = ISO.analysis.hook
wd = ISO.resilience.watchdog
elastic = ISO.resilience.elastic

E = graphmod.CollectiveEvent
G = graphmod.CollectiveGraph

_ENV = ("MPI4JAX_TPU_TELEMETRY", "MPI4JAX_TPU_TELEMETRY_DIR",
        "MPI4JAX_TPU_HEALTH", "MPI4JAX_TPU_HEALTH_INTERVAL",
        "MPI4JAX_TPU_FLIGHT_RING", "MPI4JAX_TPU_HEALTH_SUSPECTS",
        "MPI4JAX_TPU_HEALTH_PROM")


@pytest.fixture(autouse=True)
def _clean_state():
    core.set_telemetry_mode(None)
    core.reset()
    saved = {k: os.environ.pop(k, None) for k in _ENV}
    elastic.take_pending_failure()
    yield
    core.set_telemetry_mode(None)
    core.reset()
    elastic.take_pending_failure()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _arm(ring=8, interval=1, **env):
    os.environ["MPI4JAX_TPU_HEALTH"] = "on"
    os.environ["MPI4JAX_TPU_FLIGHT_RING"] = str(ring)
    os.environ["MPI4JAX_TPU_HEALTH_INTERVAL"] = str(interval)
    for k, v in env.items():
        os.environ[k] = v


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flags_registered():
    for name in ("MPI4JAX_TPU_HEALTH", "MPI4JAX_TPU_HEALTH_INTERVAL",
                 "MPI4JAX_TPU_FLIGHT_RING", "MPI4JAX_TPU_HEALTH_SUSPECTS",
                 "MPI4JAX_TPU_HEALTH_PROM"):
        assert name in config.FLAGS, name
    assert config.health_mode() == "off"
    assert config.flight_ring_capacity() == 1024
    assert config.health_interval() >= 1


def test_ring_off_is_inert():
    core.set_telemetry_mode("events")
    journal.begin("c1", 0, {"op": "allreduce", "comm_uid": 0,
                            "bytes": 8, "dtype": "float32"})
    journal.end("c1", 0, {})
    snap = health.flight_snapshot()
    assert snap == {"version": 1, "capacity": 0, "total": 0,
                    "dropped": 0, "records": []}
    assert health.ring_dropped() == 0


def test_ring_overwrites_and_counts_drops():
    _arm(ring=4)
    for i in range(10):
        health.record_event({"type": "instant", "name": f"e{i}", "t": i})
    snap = health.flight_snapshot()
    assert snap["capacity"] == 4
    assert snap["total"] == 10
    assert snap["dropped"] == 6
    # the window is the newest records, oldest first
    assert [r["name"] for r in snap["records"]] == ["e6", "e7", "e8", "e9"]


def test_ring_capacity_change_recreates():
    _arm(ring=4)
    health.record_event({"name": "a"})
    os.environ["MPI4JAX_TPU_FLIGHT_RING"] = "8"
    health.record_event({"name": "b"})
    snap = health.flight_snapshot()
    assert snap["capacity"] == 8
    assert [r["name"] for r in snap["records"]] == ["b"]


def test_events_tier_feeds_begin_and_records():
    _arm(ring=16)
    core.set_telemetry_mode("events")
    journal.begin("c1", 0, {"op": "allreduce", "comm_uid": 0,
                            "bytes": 8, "dtype": "float32"})
    kinds = [r.get("kind") or r.get("type")
             for r in health.flight_snapshot()["records"]]
    assert kinds == ["begin"]          # arrival spilled before completion
    journal.end("c1", 0, {"algo": "native"})
    journal.instant("drill", 0, {"detail": "x"})
    kinds = [r.get("kind") or r.get("type")
             for r in health.flight_snapshot()["records"]]
    assert kinds == ["begin", "op", "instant"]


def test_counters_tier_feeds_dispatch_records():
    class _Arr:
        size = 2

        class dtype:
            itemsize = 4

            def __str__(self):
                return "float32"
        dtype = dtype()

    class _Comm:
        uid = 0
        axes = ("i",)

    _arm(ring=8)
    core.set_telemetry_mode("counters")
    rec = core.open_op("allreduce", _Comm(), [_Arr()])
    core.annotate(algo="native")
    core.close_op(rec)
    recs = health.flight_snapshot()["records"]
    assert [r["kind"] for r in recs] == ["dispatch"]
    assert recs[0]["op"] == "allreduce"
    # journal stays empty in counters mode: the ring rides the counter
    # commit, it does not create journal records
    assert journal.snapshot_events() == []


# ---------------------------------------------------------------------------
# degradation detector
# ---------------------------------------------------------------------------


def _feed(key, seconds, n):
    for _ in range(n):
        health.feed_latency(key, seconds)


def test_local_degradation_detected():
    _arm()
    _feed("allreduce|0|native|float32", 0.001, 5)
    assert health._summarize_window()["findings"] == []   # builds baseline
    _feed("allreduce|0|native|float32", 0.010, 5)         # 10x slower
    found = health._summarize_window()["findings"]
    assert len(found) == 1
    f = found[0]
    assert f["kind"] == "degraded" and f["ratio"] > health.SLOW_RATIO


def test_local_degradation_needs_min_samples():
    _arm()
    _feed("k|0|n|f", 0.001, health.MIN_SAMPLES)
    health._summarize_window()
    _feed("k|0|n|f", 0.010, health.MIN_SAMPLES - 1)       # too few
    assert health._summarize_window()["findings"] == []


def _peer(proc, mean, count=5):
    return {"process": proc,
            "summary": {"allreduce|0|native|float32":
                        {"count": count, "mean": mean,
                         "p50": mean, "max": mean}}}


def test_judge_exchange_flags_slow_rank():
    peers = [_peer(0, 0.001), _peer(1, 0.001), _peer(2, 0.001),
             _peer(3, 0.005)]
    found = health.judge_exchange(peers, my_process=0)
    assert [f["rank"] for f in found] == [3]
    assert found[0]["kind"] == "slow_rank"
    assert found[0]["ratio"] == pytest.approx(5.0)


def test_judge_exchange_negative_cases():
    # within the ratio: nobody flagged
    assert health.judge_exchange(
        [_peer(0, 0.001), _peer(1, 0.0015)], 0) == []
    # below MIN_SAMPLES: not judged
    assert health.judge_exchange(
        [_peer(0, 0.001, count=1), _peer(1, 0.01, count=1)], 0) == []
    # a single process has no median to skew against
    assert health.judge_exchange([_peer(0, 0.1)], 0) == []


def test_exchange_strikes_promote_to_persistent(monkeypatch, capsys):
    _arm()
    core.set_telemetry_mode("counters")    # meters count from this tier
    peers = [_peer(0, 0.001), _peer(1, 0.001), _peer(3, 0.005)]
    monkeypatch.setattr(health, "_gather_json", lambda comm, p: peers)
    f1 = health._exchange(None, {})
    assert [f["persistent"] for f in f1] == [False]        # strike 1
    f2 = health._exchange(None, {})
    assert [f["persistent"] for f in f2] == [True]         # strike 2
    snap = core.snapshot()
    assert snap["meters"]["health.exchanges"] == 2
    assert snap["meters"]["health.slow_ranks"] == 2
    assert snap["meters"]["health.stragglers"] == 1
    # a clean exchange clears the strikes
    monkeypatch.setattr(health, "_gather_json",
                        lambda comm, p: [_peer(0, 0.001), _peer(3, 0.001)])
    assert health._exchange(None, {}) == []
    assert health._detector.strikes == {}


def test_suspect_handoff_posts_and_raises(monkeypatch):
    """End-to-end (pure): a persistent straggler becomes a pending
    RankFailure in the elastic agreement machinery AND the boundary
    raise — the classify -> agree -> shrink entry path."""
    _arm(MPI4JAX_TPU_HEALTH_SUSPECTS="1")
    core.set_telemetry_mode("counters")
    peers = [_peer(0, 0.001), _peer(1, 0.001), _peer(3, 0.005)]
    monkeypatch.setattr(health, "_gather_json", lambda comm, p: peers)
    health._exchange(None, {})                             # strike 1
    with pytest.raises(elastic.RankFailure) as ei:
        health._exchange(None, {})                         # strike 2
    assert ei.value.suspects == frozenset({3})
    assert "persistent straggler" in ei.value.detail
    posted = elastic.take_pending_failure()
    assert posted is not None and posted.suspects == frozenset({3})
    assert core.snapshot()["meters"]["health.suspects_posted"] == 1


def test_suspects_off_never_raises(monkeypatch):
    _arm()                                                 # no SUSPECTS
    peers = [_peer(0, 0.001), _peer(1, 0.001), _peer(3, 0.005)]
    monkeypatch.setattr(health, "_gather_json", lambda comm, p: peers)
    health._exchange(None, {})
    found = health._exchange(None, {})                     # persistent...
    assert [f["persistent"] for f in found] == [True]      # ...but no raise
    assert elastic.take_pending_failure() is None


def test_on_boundary_interval_gating():
    _arm(interval=3)
    assert health.on_boundary(0) is None                   # 1: not due
    assert health.on_boundary(1) is None                   # 2: not due
    assert health.on_boundary(2) == []                     # 3: due
    assert health._detector.boundaries == 3
    # off: no ticks at all
    os.environ["MPI4JAX_TPU_HEALTH"] = "off"
    assert health.on_boundary(3) is None
    assert health._detector.boundaries == 3


# ---------------------------------------------------------------------------
# postmortem bundles + CLI
# ---------------------------------------------------------------------------


def test_dump_postmortem_requires_dir():
    _arm()
    assert health.dump_postmortem("no dir") is None


def test_dump_postmortem_accumulates_reasons(tmp_path):
    _arm(ring=8)
    os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = str(tmp_path)
    core.set_telemetry_mode("events")
    journal.begin("c1", 0, {"op": "allreduce", "comm_uid": 0,
                            "bytes": 8, "dtype": "float32"})
    journal.end("c1", 0, {})
    p1 = health.dump_postmortem("first")
    p2 = health.dump_postmortem("second")
    assert p1 == p2
    bundle = json.loads(pathlib.Path(p1).read_text())
    assert bundle["schema"] == "mpx-postmortem/1"
    assert bundle["reasons"] == ["first", "second"]
    assert bundle["flight"]["records"]                     # ring captured
    assert bundle["dropped"] == {"journal": 0, "flight_ring": 0}
    assert "MPI4JAX_TPU_HEALTH" in bundle["config"]["env"]
    assert core.snapshot()["meters"]["health.postmortems"] == 2


def test_watchdog_expiry_triggers_incident_and_bundle(tmp_path):
    _arm()
    os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = str(tmp_path)
    core.set_telemetry_mode("events")
    health.on_watchdog_expiry({"opname": "allreduce", "call_id": "c7",
                               "rank": 2, "elapsed": 31.0, "timeout": 30.0})
    events = journal.snapshot_events()
    assert [e["name"] for e in events] == ["health"]
    assert "c7 stalled" in events[0]["detail"]
    assert core.snapshot()["meters"]["health.stalls"] == 1
    assert list(tmp_path.glob("postmortem-p*.json"))


def test_on_rank_failed_names_each_rank():
    _arm()
    core.set_telemetry_mode("events")
    det = health._detector
    with det.lock:
        det.strikes[3] = 2
        det.strikes[1] = 1
    health.on_rank_failed(frozenset({3, 5}), "connection reset")
    details = [e["detail"] for e in journal.snapshot_events()]
    assert len(details) == 2
    assert any("rank 3 agreed failed" in d for d in details)
    assert any("rank 5 agreed failed" in d for d in details)
    assert core.snapshot()["meters"]["health.ranks_failed"] == 2
    # the agreed verdict settles the question: strikes for the failed
    # ranks are dropped so a removed rank can never re-raise a suspect
    with det.lock:
        assert 3 not in det.strikes
        assert det.strikes.get(1) == 1


def _hang_bundles(tmp_path):
    """Two bundles imitating the CI drill: rank 0 finished c2 and began
    c3; rank 3 journaled a fault incident and never began c3."""
    base = 100.0

    def op(rank, cid, t0, dur, seq=0):
        return {"type": "op", "op": "allreduce", "call_id": cid,
                "seq": seq, "rank": rank, "process": rank,
                "t_begin": t0, "t_end": t0 + dur, "latency": dur,
                "bytes": 64, "dtype": "float32", "algo": "native"}

    def begin(rank, cid, t0):
        return {"kind": "begin", "call_id": cid, "rank": rank,
                "op": "allreduce", "t": t0, "mono": t0}

    b0 = {"schema": "mpx-postmortem/1", "process": 0,
          "reason": "watchdog_expired: allreduce call c3",
          "reasons": ["watchdog_expired: allreduce call c3"],
          "t": base + 40, "snapshot": {},
          "flight": {"version": 1, "capacity": 8, "total": 3,
                     "dropped": 0,
                     "records": [op(0, "c2", base, 0.01),
                                 begin(0, "c3", base + 1)]},
          "dropped": {"journal": 0, "flight_ring": 0},
          "watchdog_inflight": [{"opname": "allreduce", "call_id": "c3",
                                 "rank": 0, "elapsed": 31.0,
                                 "timeout": 30.0}]}
    b3 = {"schema": "mpx-postmortem/1", "process": 3,
          "reason": "fault: hang injected in MPI_Allreduce on rank 3",
          "reasons": ["fault: hang injected in MPI_Allreduce on rank 3"],
          "t": base + 2, "snapshot": {},
          "flight": {"version": 1, "capacity": 8, "total": 2,
                     "dropped": 0,
                     "records": [op(3, "c2", base, 0.01),
                                 {"type": "instant", "name": "fault",
                                  "rank": 3, "process": 3, "t": base + 0.5,
                                  "detail": "hang injected"}]},
          "dropped": {"journal": 2, "flight_ring": 0}}
    for b in (b0, b3):
        (tmp_path / f"postmortem-p{b['process']}.json").write_text(
            json.dumps(b))
    return b0, b3


def test_postmortem_report_attributes_hung_rank(tmp_path):
    _hang_bundles(tmp_path)
    bundles = merge.read_bundles(str(tmp_path))
    assert [b["process"] for b in bundles] == [0, 3]
    report = merge.postmortem_report(bundles)
    # frontier: c3 began on rank 0, never on rank 3
    fr = report["frontier"]
    assert fr["call_id"] == "c3"
    assert 0 in fr["began"] and fr["missing"] == [3]
    # attribution order: the fault incident names rank 3 first
    assert report["suspects"][0]["rank"] == 3
    assert "fault" in report["suspects"][0]["why"]
    text = merge.render_postmortem(report)
    assert "MISSING: rank(s) 3" in text
    assert "suspected straggler: rank 3" in text
    assert "2 journal record(s)" in text                   # dropped surfaced


def test_postmortem_cli_exit_codes(tmp_path, capsys):
    assert merge.main(["postmortem", str(tmp_path)]) == 2  # no bundles
    assert "no postmortem-p" in capsys.readouterr().err
    _hang_bundles(tmp_path)
    out = tmp_path / "report.txt"
    assert merge.main(["postmortem", str(tmp_path),
                       "--out", str(out)]) == 0
    printed = capsys.readouterr().out
    assert "suspected straggler: rank 3" in printed
    assert out.read_text() == printed
    # malformed bundle: loud exit 2 (the CI contract)
    (tmp_path / "postmortem-p9.json").write_text("{nope")
    assert merge.main(["postmortem", str(tmp_path)]) == 2


def test_merge_cli_warns_on_dropped(tmp_path, capsys):
    rec = {"type": "op", "op": "allreduce", "call_id": "c1", "seq": 0,
           "rank": 0, "process": 0, "t_begin": 1.0, "t_end": 1.1,
           "latency": 0.1, "bytes": 8, "dtype": "float32",
           "algo": "native"}
    (tmp_path / "events-p0.jsonl").write_text(json.dumps(rec) + "\n")
    _hang_bundles(tmp_path)                                # journal: 2
    assert merge.main(["merge", str(tmp_path), "--no-skew"]) == 0
    captured = capsys.readouterr()
    assert "merged 1 records" in captured.out
    assert "dropped records" in captured.err
    assert "journal: 2" in captured.err


# ---------------------------------------------------------------------------
# dropped surfacing (meter / snapshot / report)
# ---------------------------------------------------------------------------


def test_journal_drop_bumps_meter_and_snapshot(monkeypatch):
    monkeypatch.setattr(journal, "MAX_RECORDS", 3)
    core.set_telemetry_mode("events")
    for i in range(5):
        journal.instant(f"e{i}", 0, {})
    assert journal.dropped_records() == 2
    snap = core.snapshot()
    assert snap["meters"]["telemetry.dropped"] == 2
    assert snap["dropped"] == {"journal": 2, "flight_ring": 0}
    text = treport.render([snap])
    assert "dropped: 2 journal record(s)" in text


def test_healthy_snapshot_has_no_dropped_key():
    core.set_telemetry_mode("counters")
    snap = core.snapshot()
    assert "dropped" not in snap
    assert "dropped:" not in treport.render([snap])


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_text_renders():
    _arm()
    core.set_telemetry_mode("counters")
    core.meter("health.postmortems")
    health.set_gauge("serving_slo_headroom_ms", 12.5)
    health.set_gauge("serving_kv_occupancy", 0.75)
    text = health.prometheus_text()
    assert text.endswith("\n")
    assert 'mpx_meter_total{name="health.postmortems"} 1' in text
    assert 'mpx_dropped_records_total{source="journal"} 0' in text
    assert 'mpx_dropped_records_total{source="flight_ring"} 0' in text
    assert "mpx_serving_slo_headroom_ms 12.5" in text
    assert "mpx_serving_kv_occupancy 0.75" in text
    assert "mpx_health_boundaries_total 0" in text
    # deterministic: two renders are identical
    assert text == health.prometheus_text()


def test_prom_file_written_at_due_boundary(tmp_path):
    _arm(interval=1, MPI4JAX_TPU_HEALTH_PROM="1")
    os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = str(tmp_path)
    health.on_boundary(0)
    files = list(tmp_path.glob("prom-p*.prom"))
    assert len(files) == 1
    assert "mpx_health_boundaries_total 1" in files[0].read_text()


# ---------------------------------------------------------------------------
# MPX143: flight ring smaller than one iteration's collectives
# ---------------------------------------------------------------------------


def _loop_graph(n_events, ring):
    events = [E(index=i, op="allreduce", payload_bytes=64,
                dtype="float32", shape=(2,), loop=7, unroll=4)
              for i in range(n_events)]
    meta = {"flight_ring": ring} if ring else {}
    return G(events=events, meta=meta)


def test_mpx143_fires_when_ring_too_small():
    # ring 8 -> implied 4 collectives/iteration; 5 exceeds it
    found = checkers.check_flight_ring_capacity(_loop_graph(5, ring=8))
    assert [f.code for f in found] == ["MPX143"]
    f = found[0]
    assert "5 collectives" in f.message or "5" in f.message
    assert "MPI4JAX_TPU_FLIGHT_RING" in f.suggestion
    assert "10" in f.suggestion                            # 2 * count


def test_mpx143_negative_cases():
    # exactly at capacity: no finding
    assert checkers.check_flight_ring_capacity(_loop_graph(4, ring=8)) == []
    # health off: no flight_ring meta -> checker inert
    assert checkers.check_flight_ring_capacity(_loop_graph(50, ring=0)) == []
    # events outside any loop don't imply a per-iteration rate
    g = G(events=[E(index=i, op="allreduce") for i in range(50)],
          meta={"flight_ring": 8})
    assert checkers.check_flight_ring_capacity(g) == []


def test_mpx143_through_run_checkers_and_catalog():
    found = [f for f in checkers.run_checkers(_loop_graph(5, ring=8))
             if f.code == "MPX143"]
    assert len(found) == 1
    info = areport.CODES["MPX143"]
    assert info.severity == areport.ADVISORY
    assert "flight ring" in info.title


def test_config_snapshot_gains_flight_ring_only_when_armed():
    snap = hook.config_snapshot()
    assert "flight_ring" not in snap                       # off: identical
    _arm(ring=32)
    snap = hook.config_snapshot()
    assert snap["flight_ring"] == 32


# ---------------------------------------------------------------------------
# off-is-free invariants
# ---------------------------------------------------------------------------


def test_cache_token_unchanged_by_health():
    token_off = core.telemetry_cache_token()
    _arm()
    assert core.telemetry_cache_token() == token_off       # still (mode,)
    assert core.telemetry_cache_token() == (core.effective_mode(),)


def test_health_flags_in_env_fingerprint():
    fp_off = config.env_fingerprint()
    _arm()
    assert config.env_fingerprint() != fp_off              # retrace forced
