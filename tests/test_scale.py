"""Pod-scale gate: the lowerings must stay O(log n), not O(n).

BASELINE.md's north star is a v4-32+ pod; the suite's 8-device mesh cannot
catch a lowering that unrolls over the world size (the round-3/4 exotic-op
allreduce did exactly that: AllGather + a python fold emitting an O(world)
serial op chain).  This file spawns ONE subprocess with a 64-virtual-device
CPU mesh and pins, for the doubling-butterfly family:

- correctness at n = 64 (PROD, non-commutative matmul, unequal color
  split allreduce/bcast/scan, whole-world and per-group sendrecv rings);
- program size: the traced jaxpr's ppermute count is O(log n) —
  2·ceil(log2 64) + broadcast rounds, not O(64);
- a trace+compile+run wall budget, which an O(world) unroll blows.
"""

import json
import os
import subprocess
import sys

import pytest

from envcheck import jax_meets_package_floor, subprocess_import_skip_reason

# the 64-device subprocess imports mpi4jax_tpu; below the package's jax
# floor that import refuses by design (container-environment-only failure)
pytestmark = pytest.mark.skipif(
    not jax_meets_package_floor(), reason=subprocess_import_skip_reason()
)

_SCRIPT = r"""
import json, time
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=64"
)
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import mpi4jax_tpu as mpx

t0 = time.time()
N = 64
mesh = mpx.make_world_mesh()
comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
assert comm.Get_size() == N

# unequal color split: 3 groups of sizes 32 / 21 / 11
colors = [0 if r < 32 else (1 if r < 53 else 2) for r in range(N)]
split = comm.Split(colors)
groups = split.groups

@mpx.spmd(comm=comm)
def prog(x, mats):
    p, tok = mpx.allreduce(x, op=mpx.PROD, comm=comm)
    mm, tok = mpx.allreduce(mats, op=jnp.matmul, comm=comm, token=tok)
    gs, tok = mpx.allreduce(x, op=mpx.PROD, comm=split, token=tok)
    gb, tok = mpx.bcast(x, 2, comm=split, token=tok)
    gc, tok = mpx.scan(x, mpx.SUM, comm=split, token=tok)
    rr, tok = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm, token=tok)
    gr, tok = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=split, token=tok)
    return p, mm, gs, gb, gc, rr, gr

x = (1.0 + jnp.arange(N)[:, None] / 64.0).astype(jnp.float32)
rng = np.random.default_rng(0)
mats = jnp.asarray(
    (np.eye(2) + 0.01 * rng.normal(size=(N, 2, 2))).astype(np.float32)
)

# program-size gate: count ppermutes and total equations in the trace
jaxpr_text = str(jax.make_jaxpr(prog)(x, mats))
n_ppermute = jaxpr_text.count("ppermute")
n_lines = len(jaxpr_text.splitlines())

p, mm, gs, gb, gc, rr, gr = (np.asarray(v) for v in prog(x, mats))
wall = time.time() - t0

xs = np.asarray(x)[:, 0]
ok = bool(np.allclose(p[:, 0], np.prod(xs), rtol=1e-4))
expected_mm = np.eye(2, dtype=np.float32)
for r in range(N):
    expected_mm = expected_mm @ np.asarray(mats)[r]
ok = ok and bool(np.allclose(mm[0], expected_mm, rtol=1e-3, atol=1e-4))
for members in groups:
    want = np.prod(xs[list(members)])
    ok = ok and bool(np.allclose(gs[list(members), 0], want, rtol=1e-4))
    ok = ok and bool(
        np.allclose(gb[list(members), 0], xs[members[2]])
    )
    pref = np.cumsum(xs[list(members)])
    ok = ok and bool(np.allclose(gc[list(members), 0], pref, rtol=1e-4))
    # per-group ring: local index i receives from i-1 (mod group size)
    for i, r in enumerate(members):
        ok = ok and bool(
            gr[r, 0] == xs[members[(i - 1) % len(members)]]
        )
ok = ok and bool(np.allclose(rr[:, 0], np.roll(xs, 1)))

print(json.dumps({"ok": ok, "n_ppermute": n_ppermute,
                  "n_lines": n_lines, "wall_s": wall}))
"""


def test_64_device_log_depth_budget():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["ok"], res
    # 5 butterfly/prefix ops x <= 14 log2(64)-rounds each + 2 single-
    # permute sendrecvs (measured 46); an O(n) permute ladder needs 315+
    assert res["n_ppermute"] <= 72, res
    # total program size catches O(world) unrolls that emit NO permutes
    # (the old AllGather+fold chain): measured ~700 lines log-depth; a
    # 5-op x 64-rank fold adds 320+ combine eqns on top
    assert res["n_lines"] <= 850, res
    # measured ~3 s; an O(world) trace/compile blows this long before a pod
    assert res["wall_s"] < 120, res
