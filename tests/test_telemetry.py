"""Telemetry-layer tests (docs/observability.md).

Covers mpi4jax_tpu/telemetry/:

- tier resolution (``MPI4JAX_TPU_TELEMETRY`` env + programmatic
  override) and the cache token every compiled-program cache key folds;
- the counter registry: per-(op, comm, algo, dtype) call/byte counting,
  the eager-capture per-call semantics, infrastructure meters;
- log2 latency histograms: bucket edges, the merge property (bucket-wise
  sum, exact count/sum/min/max sidecars), quantile bounds, dict
  round-trips;
- the events journal: FIFO begin/end pairing under call-id aliasing,
  seq assignment, JSONL writing, instant (incident) events;
- the merge CLI: JSONL validation (malformed input fails loudly — the
  CI contract), Chrome-trace rendering (rank = pid, op rows = tids),
  cross-rank skew + straggler attribution, and a golden-file pin of the
  full merge (tests/data/telemetry/ → telemetry_golden_trace.json);
- through the real dispatch (JAX half): counter correctness on the
  token / notoken / eager paths, the HLO byte-identity pin for
  off/counters (and non-identity for events), per-rank journal records
  on the 8-device mesh, ``report()``'s skew table, ``cache_stats()``
  hit/miss/eviction accounting, and mode-flip retraces.

The pure half loads the telemetry modules under a private package name
(``_load_isolated``) so it runs even where the installed JAX is below
the package floor; the JAX-integration half skips there (mirroring
tests/test_resilience.py).
"""

import importlib
import json
import os
import pathlib
import sys
import time
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"
DATA = REPO / "tests" / "data"

try:
    import mpi4jax_tpu as _mpx_probe  # noqa: F401

    HAS_MPX = True
except RuntimeError:  # JAX below the package floor (utils/jax_compat.py)
    HAS_MPX = False

needs_mpx = pytest.mark.skipif(
    not HAS_MPX, reason="mpi4jax_tpu import refused (JAX below hard floor)"
)

_ISO_NAME = "_mpx_telemetry_iso"


def _load_isolated():
    """Load the pure telemetry modules under a private package name (same
    trick as tests/test_resilience.py): bypasses the package __init__'s
    JAX-floor check while preserving relative imports, and isolates
    module state from any real ``mpi4jax_tpu`` import in this process."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "telemetry"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "telemetry.hist",
        "telemetry.health",
        "telemetry.core",
        "telemetry.journal",
        "telemetry.merge",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = ISO.utils.config
hist = ISO.telemetry.hist
core = ISO.telemetry.core
journal = ISO.telemetry.journal
merge = ISO.telemetry.merge


class FakeComm:
    def __init__(self, uid=0, axes=("i",)):
        self.uid = uid
        self.axes = axes


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    """Every test starts and ends with no override, empty counters and
    journal, and no telemetry environment variables."""
    core.set_telemetry_mode(None)
    core.reset()
    saved = {
        k: os.environ.pop(k, None)
        for k in ("MPI4JAX_TPU_TELEMETRY", "MPI4JAX_TPU_TELEMETRY_DIR",
                  "MPI4JAX_TPU_HEALTH", "MPI4JAX_TPU_HEALTH_INTERVAL",
                  "MPI4JAX_TPU_FLIGHT_RING", "MPI4JAX_TPU_HEALTH_SUSPECTS",
                  "MPI4JAX_TPU_HEALTH_PROM")
    }
    yield
    core.set_telemetry_mode(None)
    core.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# mode resolution + cache token
# ---------------------------------------------------------------------------


def test_mode_default_env_and_override():
    assert core.effective_mode() == "off"
    os.environ["MPI4JAX_TPU_TELEMETRY"] = "counters"
    assert core.effective_mode() == "counters"
    assert config.telemetry_mode() == "counters"
    core.set_telemetry_mode("events")           # override shadows env
    assert core.effective_mode() == "events"
    core.set_telemetry_mode(None)               # env rules again
    assert core.effective_mode() == "counters"
    os.environ["MPI4JAX_TPU_TELEMETRY"] = "bogus"
    with pytest.raises(ValueError, match="MPI4JAX_TPU_TELEMETRY"):
        core.effective_mode()
    with pytest.raises(ValueError, match="telemetry mode"):
        core.set_telemetry_mode("bogus")


def test_telemetry_dir_parsing():
    assert config.telemetry_dir() == ""
    os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = "  /tmp/x  "
    assert config.telemetry_dir() == "/tmp/x"


def test_cache_token_reflects_mode():
    tokens = set()
    for mode in ("off", "counters", "events"):
        core.set_telemetry_mode(mode)
        tokens.add(core.telemetry_cache_token())
    # each tier must change the compiled-program cache key, or flipping
    # it would silently keep serving the old program
    assert len(tokens) == 3


# ---------------------------------------------------------------------------
# counters + meters
# ---------------------------------------------------------------------------


def test_meters_gated_by_mode():
    core.meter("x.y")                           # off: dropped
    core.set_telemetry_mode("counters")
    core.meter("x.y")
    core.meter("x.y", 2)
    assert core.snapshot()["meters"] == {"x.y": 3}
    core.reset()
    assert core.snapshot()["meters"] == {}


def test_op_record_lifecycle_counts_traced_dispatch():
    import numpy as np

    core.set_telemetry_mode("counters")
    rec = core.open_op("allreduce", FakeComm(uid=3),
                       (np.ones((8,), np.float32),))
    core.annotate(algo="ring")
    core.close_op(rec)
    snap = core.snapshot()
    (key,) = snap["ops"]
    assert key == "allreduce|3|ring|float32"
    row = snap["ops"][key]
    assert row["calls"] == 1 and row["bytes"] == 32
    assert snap["meters"]["algo.allreduce.ring"] == 1
    # off: open_op refuses (zero-cost default)
    core.set_telemetry_mode(None)
    assert core.open_op("allreduce", FakeComm(), ()) is None


def test_abort_discards_open_record():
    core.set_telemetry_mode("counters")
    rec = core.open_op("bcast", FakeComm(), ())
    core.abort_op(rec)
    assert core.snapshot()["ops"] == {}


def test_eager_capture_counts_per_call_not_per_trace():
    import numpy as np

    core.set_telemetry_mode("counters")
    cell = core.EagerCell()
    x = np.ones((4,), np.float32)
    sig = core.call_signature((x,))
    # first call: traces (record captured on the cell, not counted)
    with core.capture_eager(cell, sig):
        rec = core.open_op("allreduce", FakeComm(), (x,))
        core.annotate(algo="butterfly")
        core.close_op(rec)
    assert core.snapshot()["ops"] == {}
    core.count_eager_call(cell, sig)            # ...the dispatch loop counts
    # second call: pure cache hit — no trace, count from the stash
    with core.capture_eager(cell, sig):
        pass
    core.count_eager_call(cell, sig)
    (row,) = core.snapshot()["ops"].values()
    assert row["calls"] == 2 and row["bytes"] == 32
    assert row["algo"] == "butterfly"


def test_eager_capture_stash_is_per_signature():
    """Regression: a shape-alternating eager workload must count each
    call with ITS shape's bytes/algo — the stash of the most recent
    trace must not leak onto hits of a different signature."""
    import numpy as np

    core.set_telemetry_mode("counters")
    cell = core.EagerCell()
    small = np.ones((4,), np.float32)
    big = np.ones((1024,), np.float32)
    for x, algo in ((small, "butterfly"), (big, "ring")):
        sig = core.call_signature((x,))
        with core.capture_eager(cell, sig):     # each shape traces once
            rec = core.open_op("allreduce", FakeComm(), (x,))
            core.annotate(algo=algo)
            core.close_op(rec)
        core.count_eager_call(cell, sig)
    # now a pure hit with the SMALL shape again (no retrace)
    sig = core.call_signature((small,))
    with core.capture_eager(cell, sig):
        pass
    core.count_eager_call(cell, sig)
    rows = {r["algo"]: r for r in core.snapshot()["ops"].values()}
    assert rows["butterfly"]["calls"] == 2          # small counted twice
    assert rows["butterfly"]["bytes"] == 2 * 16     # with ITS bytes
    assert rows["ring"]["calls"] == 1
    assert rows["ring"]["bytes"] == 4096


def test_eager_capture_exception_does_not_poison_stash():
    import numpy as np

    core.set_telemetry_mode("counters")
    cell = core.EagerCell()
    x = np.ones((4,), np.float32)
    sig = core.call_signature((x,))
    with core.capture_eager(cell, sig):
        rec = core.open_op("allreduce", FakeComm(), (x,))
        core.annotate(algo="butterfly")
        core.close_op(rec)
    with pytest.raises(RuntimeError):
        with core.capture_eager(cell, sig):
            rec = core.open_op("allreduce", FakeComm(), (x,))
            core.close_op(rec)                  # partial retrace...
            raise RuntimeError("boom")          # ...then the call dies
    # the good stash survives: later hits still count the full record set
    core.count_eager_call(cell, sig)
    (row,) = core.snapshot()["ops"].values()
    assert row["calls"] == 1 and row["algo"] == "butterfly"


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


def test_bucket_index_edges():
    assert hist.bucket_index(1.0) == 0
    assert hist.bucket_index(1.5) == 0
    assert hist.bucket_index(2.0) == 1
    assert hist.bucket_index(0.5) == -1
    assert hist.bucket_index(1e-6) == -20
    assert hist.bucket_index(0.0) == hist.MIN_BUCKET      # clamp
    assert hist.bucket_index(-1.0) == hist.MIN_BUCKET     # clamp
    assert hist.bucket_index(1e30) == hist.MAX_BUCKET     # clamp
    lo = hist.bucket_value(0)
    assert 1.0 < lo < 2.0                                 # geometric mid


def test_histogram_merge_property():
    import random

    rng = random.Random(1234)
    a = [rng.uniform(1e-7, 1e-2) for _ in range(200)]
    b = [rng.uniform(1e-6, 1e-1) for _ in range(137)]
    ha, hb, hall = hist.Histogram(), hist.Histogram(), hist.Histogram()
    for v in a:
        ha.record(v)
        hall.record(v)
    for v in b:
        hb.record(v)
        hall.record(v)
    merged = ha.merge(hb)
    # merge == record-everything, exactly (fixed buckets: no rebinning)
    assert merged.counts == hall.counts
    assert merged.count == hall.count == 337
    assert merged.sum == pytest.approx(hall.sum)
    assert merged.min == hall.min and merged.max == hall.max
    # inputs untouched
    assert ha.count == 200 and hb.count == 137
    # quantiles are bucket estimates clamped into [min, max], monotone
    q = [merged.quantile(x) for x in (0.0, 0.5, 0.9, 0.99, 1.0)]
    assert all(merged.min <= v <= merged.max for v in q)
    assert q == sorted(q)


def test_histogram_dict_round_trip_and_single_sample():
    h = hist.Histogram()
    h.record(3.5e-4)
    d = h.to_dict()
    h2 = hist.Histogram.from_dict(json.loads(json.dumps(d)))
    assert h2.counts == h.counts and h2.count == 1
    assert h2.min == h2.max == 3.5e-4
    # a single-sample histogram reports its sample, not a bucket midpoint
    assert h2.quantile(0.5) == 3.5e-4
    assert hist.Histogram().quantile(0.5) is None


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

_META = {"op": "allreduce", "comm_uid": "0", "axes": ["i"], "bytes": 64,
         "dtype": "float32"}


def test_journal_fifo_aliasing_and_seq():
    core.set_telemetry_mode("events")
    # two begins under ONE call id before any end (a fori_loop trace site)
    journal.begin("0000000a", 0, _META)
    journal.begin("0000000a", 0, _META)
    journal.end("0000000a", 0, {"algo": "ring"})
    journal.end("0000000a", 0, {"algo": "ring"})
    recs = journal.snapshot_events()
    assert [r["seq"] for r in recs] == [0, 1]
    assert all(r["type"] == "op" and r["op"] == "allreduce" for r in recs)
    assert all(r["latency"] >= 0 for r in recs)
    assert all(r["t_end"] >= r["t_begin"] for r in recs)
    assert recs[0]["algo"] == "ring" and recs[0]["bytes"] == 64
    # latency fed the per-op histogram under the annotated key
    snap = core.snapshot()
    assert snap["ops"]["allreduce|0|ring|float32"]["latency"]["count"] == 2
    # unmatched end after a reset is dropped, not an error
    journal.reset()
    journal.end("0000000a", 0, {})
    assert journal.snapshot_events() == []


def test_journal_instant_gated_by_events_tier():
    journal.instant("fault", 1, {"detail": "x"})          # off: dropped
    core.set_telemetry_mode("counters")
    journal.instant("fault", 1, {"detail": "x"})          # counters: dropped
    assert journal.snapshot_events() == []
    core.set_telemetry_mode("events")
    journal.instant("fault", 1, {"detail": "x"})
    (rec,) = journal.snapshot_events()
    assert rec["type"] == "instant" and rec["name"] == "fault"
    assert rec["rank"] == 1 and "t" in rec


def test_journal_writes_jsonl(tmp_path):
    core.set_telemetry_mode("events")
    os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = str(tmp_path)
    journal.begin("0000000b", 2, _META)
    journal.end("0000000b", 2, {"algo": "native"})
    journal.flush()
    (path,) = tmp_path.glob("*.jsonl")
    assert path.name.startswith(journal.JOURNAL_FILE_PREFIX)
    (line,) = path.read_text().splitlines()
    rec = json.loads(line)
    assert rec["op"] == "allreduce" and rec["rank"] == 2
    for field in ("call_id", "seq", "t_begin", "t_end", "latency",
                  "process"):
        assert field in rec
    core.reset()  # closes the file handle


# ---------------------------------------------------------------------------
# merge + chrome trace + skew
# ---------------------------------------------------------------------------


def _write_journal(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _op_rec(rank, t0, dur, cid="00000001", seq=0, op="allreduce", **kw):
    return dict(
        {"type": "op", "op": op, "call_id": cid, "seq": seq, "rank": rank,
         "process": rank, "t_begin": t0, "t_end": t0 + dur,
         "latency": dur, "bytes": 64, "dtype": "float32", "algo": "ring"},
        **kw,
    )


def test_merge_validates_malformed_lines(tmp_path):
    p = tmp_path / "events-p0.jsonl"
    p.write_text('{"type": "op"\n')                       # not JSON
    with pytest.raises(merge.MalformedJournal, match="events-p0.jsonl:1"):
        merge.read_journal(str(p))
    p.write_text('{"type": "nope"}\n')
    with pytest.raises(merge.MalformedJournal, match="unknown record type"):
        merge.read_journal(str(p))
    p.write_text('{"type": "op", "op": "allreduce"}\n')
    with pytest.raises(merge.MalformedJournal, match="missing field"):
        merge.read_journal(str(p))
    p.write_text('[1, 2]\n')
    with pytest.raises(merge.MalformedJournal, match="JSON object"):
        merge.read_journal(str(p))
    # empty dir is an error too (nothing to merge)
    with pytest.raises(FileNotFoundError):
        merge.merge_dir(str(tmp_path / "empty"))


def test_merge_dedupes_and_sorts(tmp_path):
    a = _op_rec(0, 10.0, 0.5)
    b = _op_rec(1, 10.2, 0.5)
    _write_journal(tmp_path / "events-p0.jsonl", [a, a])  # dup in-file
    _write_journal(tmp_path / "events-p1.jsonl", [b])
    recs = merge.merge_dir(str(tmp_path))
    assert [r["rank"] for r in recs] == [0, 1]            # t_begin order


def test_chrome_trace_structure_and_skew():
    recs = [
        _op_rec(0, 10.000, 0.5),
        _op_rec(1, 10.002, 0.5),
        _op_rec(0, 11.000, 0.3, seq=1),
        _op_rec(1, 11.010, 0.3, seq=1),
        {"type": "instant", "name": "fault", "rank": 1, "process": 1,
         "t": 10.9, "detail": "delay injected"},
    ]
    trace = merge.chrome_trace(recs)
    events = trace["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    inst = [e for e in events if e["ph"] == "i"]
    assert len(xs) == 4 and len(inst) == 1
    # rank = pid; op rows = tids (one per op name, consistent across pids)
    assert {e["pid"] for e in xs} == {0, 1}
    assert len({e["tid"] for e in xs}) == 1                # one op name
    assert xs[0]["dur"] == pytest.approx(0.5 * 1e6)        # µs
    assert min(e["ts"] for e in xs) == 0.0                 # rebased
    names = {(m["name"], m.get("pid"), m.get("tid")) for m in metas}
    assert ("process_name", 0, None) in names
    assert any(m["name"] == "thread_name" and
               m["args"]["name"] == "allreduce" for m in metas)
    assert inst[0]["s"] == "p" and inst[0]["pid"] == 1

    table = merge.skew_table(recs)
    row = table["per_op"]["allreduce"]
    assert row["groups"] == 2
    assert row["max_skew"] == pytest.approx(0.010)
    assert row["mean_skew"] == pytest.approx(0.006)
    assert table["per_rank"][1]["last_arrivals"] == 2      # the straggler
    assert table["per_rank"][0]["last_arrivals"] == 0
    text = merge.render_skew(table)
    assert "allreduce" in text and "r1" in text


def test_skew_needs_two_ranks():
    table = merge.skew_table([_op_rec(0, 1.0, 0.1)])
    assert table["per_op"] == {} and table["per_rank"] == {}
    assert "2 ranks" in merge.render_skew(table)


def test_merge_cli_end_to_end(tmp_path, capsys):
    _write_journal(tmp_path / "events-p0.jsonl",
                   [_op_rec(0, 10.0, 0.5)])
    _write_journal(tmp_path / "events-p1.jsonl",
                   [_op_rec(1, 10.1, 0.5)])
    out = tmp_path / "trace.json"
    rc = merge.main(["merge", str(tmp_path), "--perfetto", str(out)])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "2 rank(s)" in printed and "last arrivals" in printed
    trace = json.loads(out.read_text())
    assert trace["traceEvents"]
    # malformed input: non-zero exit, error on stderr (the CI contract)
    (tmp_path / "events-p2.jsonl").write_text("garbage\n")
    rc = merge.main(["merge", str(tmp_path), "--no-skew"])
    captured = capsys.readouterr()
    assert rc == 2 and "events-p2.jsonl:1" in captured.err


def test_merge_golden_file():
    """Full-merge pin: the committed 2-process journals render to exactly
    the committed Chrome trace (deterministic ordering + rebasing)."""
    recs = merge.merge_dir(str(DATA / "telemetry"))
    got = merge.chrome_trace(recs)
    expected = json.loads((DATA / "telemetry_golden_trace.json").read_text())
    assert got == expected
    # and the injected 2ms straggler in the fixture is attributed
    table = merge.skew_table(recs)
    assert table["per_op"]["allreduce"]["max_skew"] == pytest.approx(
        0.002, abs=1e-4)
    assert table["per_rank"][1]["last_arrivals"] == 3


def test_chrome_trace_overlapping_spans_distinct_tracks():
    """Regression: overlapping spans on ONE rank — a megastep bracket
    enclosing async start/wait collectives that themselves overlap —
    must land on distinct thread rows (tid per op name), not nest into
    one row, and the rendered trace must stay valid Chrome-trace JSON."""
    recs = [
        # megastep bracket 10.0-11.0 encloses everything on rank 0
        _op_rec(0, 10.000, 1.0, cid="m1", op="megastep"),
        # two async allreduce spans overlapping each other AND the
        # megastep (start/wait pairs in flight simultaneously)
        _op_rec(0, 10.100, 0.6, cid="a1", op="allreduce_async"),
        _op_rec(0, 10.300, 0.6, cid="a2", seq=1, op="allreduce_async"),
        # a plain collective overlapping the tail of both
        _op_rec(0, 10.700, 0.2, cid="c1", op="psum"),
        _op_rec(1, 10.000, 1.0, cid="m1", op="megastep"),
        {"type": "instant", "name": "drill", "rank": 0, "process": 0,
         "t": 10.5, "detail": "mid-megastep"},
    ]
    trace = merge.chrome_trace(recs)
    blob = json.dumps(trace)                 # must not corrupt the JSON
    assert json.loads(blob) == trace
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    r0 = [e for e in xs if e["pid"] == 0]
    # one tid per op name: megastep / allreduce_async / psum are three
    # distinct tracks, so the overlapping slices never stack in one row
    tids = {}
    for e in r0:
        tids.setdefault(e["name"].split(" ")[0].split("#")[0], set()).add(
            e["tid"])
    assert len({t for s in tids.values() for t in s}) == 3
    for name, s in tids.items():
        assert len(s) == 1, f"op {name} split across tids {s}"
    # the two async slices share a tid and genuinely overlap in time
    a = sorted((e for e in r0 if "allreduce_async" in e["name"]),
               key=lambda e: e["ts"])
    assert len(a) == 2 and a[0]["tid"] == a[1]["tid"]
    assert a[1]["ts"] < a[0]["ts"] + a[0]["dur"]
    # tid assignment is consistent across pids (megastep row lines up)
    mega_tids = {e["tid"] for e in xs if "megastep" in e["name"]}
    assert len(mega_tids) == 1
    # the instant row (tid 0) stays separate from every op row
    inst = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert inst and all(e["tid"] not in
                        {x["tid"] for x in xs} for e in inst)


# ===========================================================================
# JAX-integration half (needs a working mpi4jax_tpu import)
# ===========================================================================


@pytest.fixture
def real_telemetry():
    """Clean real-package telemetry state around a traced test."""
    import mpi4jax_tpu as mpx

    mpx.telemetry.reset()
    mpx.set_telemetry_mode(None)
    yield mpx.telemetry
    mpx.set_telemetry_mode(None)
    mpx.telemetry.reset()
    mpx.clear_caches()


def _allreduce_calls(snap):
    return sum(r["calls"] for r in snap["ops"].values()
               if r["op"] == "allreduce")


@needs_mpx
def test_counters_token_notoken_and_eager_paths(real_telemetry):
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.experimental import notoken

    telemetry = real_telemetry
    mpx.set_telemetry_mode("counters")

    # token path, traced: counts once per TRACE (the host only sees the
    # trace; the second call is a program-cache hit)
    @mpx.spmd
    def f(x):
        res, tok = mpx.allreduce(x, op=mpx.SUM)
        res2, _ = mpx.allreduce(res, op=mpx.SUM, token=tok)
        return res2

    x = jnp.ones((8, 4))
    np.asarray(f(x))
    np.asarray(f(x))
    snap = telemetry.snapshot()
    assert _allreduce_calls(snap) == 2                 # two dispatch sites
    assert snap["meters"]["spmd_cache.hits"] == 1
    assert snap["meters"]["spmd_cache.misses"] == 1
    assert snap["meters"]["recompiles.spmd.f"] == 1

    # notoken path rides the same dispatch
    @mpx.spmd
    def g(x):
        return notoken.allreduce(x, op=mpx.SUM)

    np.asarray(g(x))
    assert _allreduce_calls(telemetry.snapshot()) == 3

    # eager path: counts once per CALL, cache hit or not
    mpx.clear_caches()
    mpx.allreduce(x, op=mpx.SUM)                       # compile
    mpx.allreduce(x, op=mpx.SUM)                       # cache hit
    snap = telemetry.snapshot()
    assert _allreduce_calls(snap) == 5
    row = next(r for r in snap["ops"].values() if r["op"] == "allreduce")
    assert row["bytes"] > 0 and row["dtype"] == "float32"
    assert snap["meters"]["eager_cache.hits"] == 1
    assert snap["meters"]["eager_cache.misses"] == 1


@needs_mpx
def test_algo_selection_metered(real_telemetry):
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.set_telemetry_mode("counters")
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM)                       # native HLO path
    mpx.allreduce(x, op=mpx.PROD)                      # butterfly (small)
    meters = real_telemetry.snapshot()["meters"]
    assert meters["algo.allreduce.native"] == 1
    assert meters["algo.allreduce.butterfly"] == 1
    snap_keys = {r["algo"] for r in
                 real_telemetry.snapshot()["ops"].values()}
    assert {"native", "butterfly"} <= snap_keys


@needs_mpx
def test_hlo_byte_identical_off_and_counters(real_telemetry, monkeypatch):
    """Acceptance pin: ``off`` (default) is byte-identical to an
    uninstrumented build, ``counters`` is byte-identical to ``off``
    (host-side bookkeeping only), and ``events`` is NOT (so the pin
    cannot pass vacuously)."""
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.telemetry import core as real_core

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.ones((8, 4))
    default_off = jax.jit(f).lower(x).as_text()
    with monkeypatch.context() as m:
        # the uninstrumented build: dispatch never opens a record
        m.setattr(real_core, "open_op", lambda *a, **k: None)
        uninstrumented = jax.jit(f).lower(x).as_text()
    assert default_off == uninstrumented

    mpx.set_telemetry_mode("counters")
    counters = jax.jit(f).lower(x).as_text()
    assert counters == default_off

    mpx.set_telemetry_mode("events")
    events = jax.jit(f).lower(x).as_text()
    assert events != default_off


@needs_mpx
def test_hlo_and_cache_tokens_unchanged_by_health(real_telemetry,
                                                  monkeypatch):
    """Acceptance pin for the health plane: arming ``MPI4JAX_TPU_HEALTH``
    changes NOTHING the compiler sees — lowered HLO and the program-cache
    tokens (the telemetry token every compiled-program key folds, for
    the spmd and eager one-op caches alike) are byte-identical with the
    flag off and on, in the off AND counters telemetry tiers.  The ring
    is host-side bookkeeping riding existing hooks; only the telemetry
    *tier* may move compiled artifacts."""
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.telemetry import core as real_core

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.ones((8, 4))
    for tier in (None, "counters"):
        mpx.set_telemetry_mode(tier)
        baseline_hlo = jax.jit(f).lower(x).as_text()
        baseline_token = real_core.telemetry_cache_token()
        with monkeypatch.context() as m:
            m.setenv("MPI4JAX_TPU_HEALTH", "on")
            m.setenv("MPI4JAX_TPU_FLIGHT_RING", "64")
            assert jax.jit(f).lower(x).as_text() == baseline_hlo
            assert real_core.telemetry_cache_token() == baseline_token
        mpx.telemetry.reset()


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.05)
    return pred()


@needs_mpx
def test_events_journal_per_rank_and_merge(real_telemetry, tmp_path,
                                           monkeypatch):
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.telemetry import journal as real_journal

    monkeypatch.setenv("MPI4JAX_TPU_TELEMETRY_DIR", str(tmp_path))
    mpx.set_telemetry_mode("events")

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    jax.block_until_ready(f(jnp.ones((8, 4))))
    # end callbacks may trail block_until_ready (unordered io_callback)
    assert _wait_for(lambda: len(real_journal.snapshot_events()) >= 8)
    real_journal.flush()

    recs = [r for r in real_journal.snapshot_events() if r["type"] == "op"]
    assert {r["rank"] for r in recs} == set(range(8))
    assert all(r["op"] == "allreduce" and r["latency"] >= 0 for r in recs)
    assert all(r["bytes"] == 16 and r["dtype"] == "float32" for r in recs)
    # per-call_id cross-rank matching: all 8 ranks share one (cid, seq)
    assert len({(r["call_id"], r["seq"]) for r in recs}) == 1

    # the JSONL on disk merges into a valid Chrome trace
    mpx.telemetry.reset()  # close the journal file
    merged = merge.merge_dir(str(tmp_path))
    assert len([r for r in merged if r["type"] == "op"]) >= 8
    trace = merge.chrome_trace(merged)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == set(range(8))
    table = merge.skew_table(merged)
    assert table["per_op"]["allreduce"]["groups"] >= 1


@needs_mpx
def test_report_renders_per_op_table_with_skew(real_telemetry):
    import io

    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.telemetry import journal as real_journal

    mpx.set_telemetry_mode("events")

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    jax.block_until_ready(f(jnp.ones((8, 4))))
    assert _wait_for(lambda: len(real_journal.snapshot_events()) >= 8)

    buf = io.StringIO()
    text = mpx.telemetry.report(file=buf)
    assert buf.getvalue().strip() == text.strip()
    assert "allreduce" in text
    assert "skew us" in text and "straggler" in text
    assert "p50 us" in text and "p99 us" in text
    # the straggler column names a rank once events span the mesh
    assert " r" in text


@needs_mpx
def test_dump_writes_snapshot_json(real_telemetry, tmp_path):
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.set_telemetry_mode("counters")
    mpx.allreduce(jnp.ones((8, 4)), op=mpx.SUM)
    path = mpx.telemetry.dump(str(tmp_path / "snap.json"))
    snap = json.loads(pathlib.Path(path).read_text())
    assert snap["mode"] == "counters"
    assert any(r["op"] == "allreduce" for r in snap["ops"].values())


@needs_mpx
def test_eager_cache_stats_and_evictions(real_telemetry, monkeypatch):
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.ops import _base

    mpx.clear_caches()
    assert mpx.cache_stats() == {
        "hits": 0, "misses": 0, "evictions": 0, "size": 0,
    }
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM)
    s = mpx.cache_stats()
    assert s["misses"] == 1 and s["size"] == 1 and s["hits"] == 0
    mpx.allreduce(x, op=mpx.SUM)
    assert mpx.cache_stats()["hits"] == 1
    # shrink the LRU bound: the next distinct program must evict
    monkeypatch.setattr(_base, "_EAGER_CACHE_MAX", 1)
    mpx.allreduce(x, op=mpx.MAX)
    s = mpx.cache_stats()
    assert s["evictions"] == 1 and s["size"] == 1
    mpx.clear_caches()
    assert mpx.cache_stats() == {
        "hits": 0, "misses": 0, "evictions": 0, "size": 0,
    }


@needs_mpx
def test_mode_flip_retraces_eager_program(real_telemetry):
    """The telemetry tier is folded into the eager cache key: flipping it
    must retrace (a stale program would silently keep the old
    instrumentation)."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.clear_caches()
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM)
    mpx.set_telemetry_mode("counters")
    mpx.allreduce(x, op=mpx.SUM)
    mpx.set_telemetry_mode(None)
    mpx.allreduce(x, op=mpx.SUM)                # back to the first program
    s = mpx.cache_stats()
    assert s["misses"] == 2 and s["hits"] == 1
