"""Pure half of the AOT pinning + persistent compile cache suite
(docs/aot.md).

Everything here runs WITHOUT importing mpi4jax_tpu (the isolated loader
below, mirroring tests/test_elastic_pure.py), so the cache core is
verified under any JAX version:

- key derivation (aot/keys.py): canonicalization totality and
  determinism, per-part key sensitivity, interned-wrapper unwrapping,
  address-bearing-repr rejection;
- the artifact container + disk cache (aot/diskcache.py): round-trip,
  atomicity leftovers, corruption self-healing, LRU eviction to the
  byte cap, counter accounting, the disabled tier;
- the stale-detection state machine (aot/invalidation.py): env-flag
  mutation, set_*-override epoch bumps, elastic epoch advances, the
  MPX129 tagging, flip-back revalidation;
- the MPX128 hot-loop advisory checker and both new catalog rows.

The traced half (pinned==jit bit-identity, donation, HLO pins, the
disk round-trip through real executables, the elastic re-pin drill) is
tests/test_aot.py, which needs jax >= the package floor.
"""

import importlib
import os
import pathlib
import sys
import time
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_aot_iso"


def _load_isolated():
    """Load the pure-Python AOT stack under a private package name
    (bypasses mpi4jax_tpu/__init__.py and its JAX floor; state isolated
    from any real import in the same process)."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "telemetry", "resilience", "aot"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "analysis.report",
        "analysis.graph",
        "analysis.checkers",
        "telemetry.core",
        "resilience.elastic",
        "aot.keys",
        "aot.diskcache",
        "aot.invalidation",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
keys = ISO.aot.keys
diskcache = ISO.aot.diskcache
inv = ISO.aot.invalidation
config = ISO.utils.config
elastic = ISO.resilience.elastic
report = ISO.analysis.report
graph_mod = ISO.analysis.graph
checkers = ISO.analysis.checkers

KEY_A = "ab" * 32
KEY_B = "cd" * 32


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "compile-cache")
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", d)
    diskcache.reset_stats()
    yield d
    diskcache.reset_stats()


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------


def test_canonical_scalars_and_containers():
    assert keys.canonical(None) == "None"
    assert keys.canonical(True) == "True"
    assert keys.canonical(3) != keys.canonical(3.0)
    assert keys.canonical("3") != keys.canonical(3)
    assert keys.canonical((1, (2, "x"))) == keys.canonical([1, [2, "x"]])
    # dicts canonicalize order-independently
    assert keys.canonical({"b": 1, "a": 2}) == keys.canonical({"a": 2, "b": 1})
    assert keys.canonical({"a": 1}) != keys.canonical({"a": 2})
    assert keys.canonical(frozenset({2, 1})) == keys.canonical({1, 2})


def test_canonical_unwraps_interned_wrappers():
    class Interned:  # shape of ops/_base._Interned
        def __init__(self, key):
            self.key = key

    tok = (("MPI4JAX_TPU_FUSION", "auto"), 3, True)
    assert keys.canonical(Interned(tok)) == keys.canonical(tok)


def test_canonical_rejects_address_reprs():
    class Opaque:
        pass

    with pytest.raises(TypeError, match="memory address"):
        keys.canonical(Opaque())


def test_canonical_bytes_hash():
    assert keys.canonical(b"abc") == keys.canonical(b"abc")
    assert keys.canonical(b"abc") != keys.canonical(b"abd")
    assert keys.canonical(b"abc") != keys.canonical("abc")


def test_fingerprint_deterministic():
    assert keys.fingerprint("jaxpr text") == keys.fingerprint(b"jaxpr text")
    assert keys.fingerprint("a") != keys.fingerprint("b")
    assert len(keys.fingerprint("x")) == 64


def test_derive_key_sensitivity():
    k0 = keys.derive_key("fp", (("x",), (8,)), ("tok",), ("0.6.0", "0.6.0"))
    # identical parts -> identical key (the multi-host contract)
    assert k0 == keys.derive_key("fp", (("x",), (8,)), ("tok",),
                                 ("0.6.0", "0.6.0"))
    assert len(k0) == 64
    # every part is load-bearing
    assert k0 != keys.derive_key("FP", (("x",), (8,)), ("tok",),
                                 ("0.6.0", "0.6.0"))
    assert k0 != keys.derive_key("fp", (("x",), (4,)), ("tok",),
                                 ("0.6.0", "0.6.0"))
    assert k0 != keys.derive_key("fp", (("x",), (8,)), ("tok2",),
                                 ("0.6.0", "0.6.0"))
    assert k0 != keys.derive_key("fp", (("x",), (8,)), ("tok",),
                                 ("0.7.0", "0.6.0"))


# ---------------------------------------------------------------------------
# the artifact container
# ---------------------------------------------------------------------------


def test_container_roundtrip():
    data = diskcache.pack(b"payload bytes")
    assert diskcache.unpack(data) == b"payload bytes"
    assert diskcache.unpack(diskcache.pack(b"")) == b""


@pytest.mark.parametrize("mutation", [
    lambda d: d[:-1],                       # truncated digest
    lambda d: b"XXXXXXXX" + d[8:],          # bad magic
    lambda d: d[:20] + b"\x00" + d[21:],    # flipped payload byte
    lambda d: d[:10] + b"\xff" + d[11:],    # corrupted length
    lambda d: b"",                          # empty file
], ids=["truncated", "magic", "payload-bit", "length", "empty"])
def test_container_rejects_corruption(mutation):
    data = diskcache.pack(b"payload bytes")
    assert diskcache.unpack(mutation(data)) is None


# ---------------------------------------------------------------------------
# the disk cache
# ---------------------------------------------------------------------------


def test_disabled_tier_stores_nothing(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", raising=False)
    diskcache.reset_stats()
    assert not diskcache.enabled()
    assert diskcache.cache_root() is None
    assert diskcache.get(KEY_A) is None
    assert diskcache.put(KEY_A, b"x") is False
    st = diskcache.stats()
    # a disabled tier neither hits nor misses: it does not exist
    assert st["hits"] == st["misses"] == st["writes"] == 0
    assert st["enabled"] is False


def test_put_get_roundtrip(cache_dir):
    assert diskcache.get(KEY_A) is None          # miss
    assert diskcache.put(KEY_A, b"artifact-1")
    assert diskcache.get(KEY_A) == b"artifact-1"  # hit
    # overwrite wins (the concurrent-rank race: last writer, same bytes)
    assert diskcache.put(KEY_A, b"artifact-2")
    assert diskcache.get(KEY_A) == b"artifact-2"
    st = diskcache.stats()
    assert st["hits"] == 2 and st["misses"] == 1 and st["writes"] == 2
    assert st["entries"] == 1
    assert st["dir"] == cache_dir


def test_corrupt_artifact_self_heals(cache_dir):
    diskcache.put(KEY_A, b"good")
    path = diskcache._path_for(diskcache.cache_root(), KEY_A)
    with open(path, "wb") as f:
        f.write(b"rotten bits")
    assert diskcache.get(KEY_A) is None      # corrupt -> miss
    assert not os.path.exists(path)          # and deleted
    # the recompile path rewrites it
    assert diskcache.put(KEY_A, b"good again")
    assert diskcache.get(KEY_A) == b"good again"


def test_eviction_lru_to_byte_cap(cache_dir, monkeypatch):
    def put_aged(key, payload, age_s):
        assert diskcache.put(key, payload)
        path = diskcache._path_for(diskcache.cache_root(), key)
        t = time.time() - age_s
        os.utime(path, (t, t))
        return path

    one = len(diskcache.pack(b"x" * 64))
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES",
                       str(2 * one + 10))
    oldest = put_aged(KEY_A, b"x" * 64, 300)
    put_aged(KEY_B, b"y" * 64, 200)
    # third write exceeds the cap -> the OLDEST artifact goes, never the
    # one just written
    diskcache.put("ef" * 32, b"z" * 64)
    assert not os.path.exists(oldest)
    assert diskcache.get("ef" * 32) == b"z" * 64
    assert diskcache.get(KEY_B) == b"y" * 64
    assert diskcache.stats()["evictions"] == 1


def test_eviction_unbounded_when_zero(cache_dir, monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES", "0")
    for i in range(4):
        diskcache.put(("%02x" % i) * 32, bytes(64))
    assert diskcache.stats()["evictions"] == 0
    assert diskcache.stats()["entries"] == 4


def test_hit_touches_mtime_for_lru(cache_dir):
    path_a = None
    diskcache.put(KEY_A, b"a")
    path_a = diskcache._path_for(diskcache.cache_root(), KEY_A)
    old = time.time() - 500
    os.utime(path_a, (old, old))
    before = os.stat(path_a).st_mtime
    assert diskcache.get(KEY_A) == b"a"
    assert os.stat(path_a).st_mtime > before  # refreshed to ~now


# ---------------------------------------------------------------------------
# the stale-detection state machine
# ---------------------------------------------------------------------------


def test_stamp_current_roundtrip():
    ws = inv.WorldStamp.capture()
    assert ws.is_current()
    ws.check()  # no raise
    assert ws.describe_staleness() is None


def test_env_mutation_goes_stale_and_back(monkeypatch):
    ws = inv.WorldStamp.capture()
    monkeypatch.setenv("MPI4JAX_TPU_FUSION", "auto")
    assert not ws.is_current()
    with pytest.raises(inv.StaleProgramError) as ei:
        ws.check("pinned program 'step'")
    assert getattr(ei.value, "mpx_code", None) == "MPX129"
    assert "MPX129" in str(ei.value)
    assert "MPI4JAX_TPU_FUSION" in str(ei.value)  # names the moved flag
    # flip-back revalidates: same stamp, same trace
    monkeypatch.delenv("MPI4JAX_TPU_FUSION")
    assert ws.is_current()
    ws.check()


def test_programmatic_override_goes_stale():
    ws = inv.WorldStamp.capture()
    config.bump_config_epoch()  # what every set_* override does
    assert not ws.is_current()
    why = ws.describe_staleness()
    assert "set_*" in why or "epoch" in why
    with pytest.raises(inv.StaleProgramError):
        ws.check()
    # re-capture enters the new world
    assert inv.WorldStamp.capture().is_current()


def test_elastic_epoch_goes_stale_permanently(monkeypatch):
    ws = inv.WorldStamp.capture()
    before = elastic.current_epoch()
    elastic.advance_epoch(world=3, cause="revoke", detail="test")
    try:
        assert not ws.is_current()
        with pytest.raises(inv.StaleProgramError) as ei:
            ws.check("pinned program 'loop'")
        msg = str(ei.value)
        assert "epoch" in msg and f"{before} -> {before + 1}" in msg
        assert getattr(ei.value, "mpx_code", None) == "MPX129"
        # a fresh capture is current in the new epoch
        ws2 = inv.WorldStamp.capture()
        assert ws2.epoch == before + 1 and ws2.is_current()
    finally:
        elastic._reset_epoch_for_tests()


def test_storage_only_flags_never_stale(monkeypatch):
    # the compile-cache knobs decide where artifacts are STORED — they
    # shape no trace, so retuning them must not revoke live pins
    ws = inv.WorldStamp.capture()
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", "/tmp/somewhere")
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES", "123456")
    assert ws.is_current()
    ws.check()  # no raise
    for name in inv.STORAGE_ONLY_FLAGS:
        assert name in config.FLAGS  # exemption list stays declared


def test_check_message_names_the_repin_recipe():
    ws = inv.WorldStamp.capture()
    config.bump_config_epoch()
    with pytest.raises(inv.StaleProgramError, match="repin"):
        ws.check()


# ---------------------------------------------------------------------------
# MPX128 checker + catalog rows
# ---------------------------------------------------------------------------


def _events(n, op="allreduce", eager=False, **over):
    base = dict(comm_uid=1, reduction="sum", dtype="float32", shape=(8,))
    base.update(over)
    return [graph_mod.CollectiveEvent(index=i, op=op, eager=eager, **base)
            for i in range(n)]


def _graph(events, pinned=False):
    return graph_mod.CollectiveGraph(events=events,
                                     meta={"pinned": pinned})


def test_mpx128_fires_at_threshold():
    n = checkers.AOT_ADVISORY_MIN_REPEATS
    findings = checkers.check_unpinned_hot_loop(_graph(_events(n)))
    assert len(findings) == 1
    f = findings[0]
    assert f.code == "MPX128" and f.severity == "advisory"
    assert "mpx.compile" in f.suggestion
    assert str(n) in f.message


def test_mpx128_negative_below_threshold():
    n = checkers.AOT_ADVISORY_MIN_REPEATS - 1
    assert not checkers.check_unpinned_hot_loop(_graph(_events(n)))


def test_mpx128_gated_on_pinned_meta():
    n = checkers.AOT_ADVISORY_MIN_REPEATS
    # a trace being pinned right now must not be advised to pin itself
    assert not checkers.check_unpinned_hot_loop(
        _graph(_events(n), pinned=True))
    # hand-built graphs without the meta key are testing other rules
    assert not checkers.check_unpinned_hot_loop(
        graph_mod.CollectiveGraph(events=_events(n), meta={}))


def test_mpx128_ignores_eager_and_mixed_signatures():
    n = checkers.AOT_ADVISORY_MIN_REPEATS
    # eager ops are one-op programs, not an unrolled loop
    assert not checkers.check_unpinned_hot_loop(
        _graph(_events(n, eager=True)))
    # p2p loops are structure (one message per neighbor), never a
    # hot-loop advisory — and async spans pair, they don't repeat
    assert not checkers.check_unpinned_hot_loop(
        _graph(_events(n, op="sendrecv", reduction=None, tag=0)))
    assert not checkers.check_unpinned_hot_loop(
        _graph([graph_mod.CollectiveEvent(index=i, op="allreduce_start",
                                          comm_uid=1, reduction="sum",
                                          dtype="float32", shape=(8,),
                                          span=i)
                for i in range(n)]))
    # n distinct signatures (different shapes) never accumulate
    events = [graph_mod.CollectiveEvent(index=i, op="allreduce", comm_uid=1,
                                        reduction="sum", dtype="float32",
                                        shape=(i + 1,))
              for i in range(n)]
    assert not checkers.check_unpinned_hot_loop(_graph(events))


def test_new_codes_in_catalog():
    assert report.CODES["MPX128"].severity == report.ADVISORY
    assert report.CODES["MPX129"].severity == report.ERROR
    # the registry covers them: MPX128 via the checker, MPX129 via the
    # tagged raise site (invalidation.check) — build one of each
    exc = report.mpx_error(inv.StaleProgramError, "MPX129", "stale")
    assert exc.mpx_code == "MPX129"
    f = report.finding_from_exception(exc)
    assert f is not None and f.code == "MPX129"


def test_flags_declared():
    assert "MPI4JAX_TPU_COMPILE_CACHE_DIR" in config.FLAGS
    assert "MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES" in config.FLAGS
    assert config.compile_cache_dir() == "" or True  # readable
    assert config.compile_cache_max_bytes() >= 0
