"""Pure half of the megastep execution suite (docs/aot.md "Megastep
execution").

Everything here runs WITHOUT importing mpi4jax_tpu (the isolated loader
below, mirroring tests/test_aot_pure.py), so the loop machinery's pure
core is verified under any JAX version:

- the MPX130 span-straddle checker on hand-built graphs, the MPX128
  loop-body exemption, and both catalog rows;
- the C++ fast-path installer (aot/fastpath.py) against fake Compiled
  objects: probe order, tree fallback, factory failure -> graceful
  Python-path fallback;
- the cache-warming manifest parser (aot/warm.py): schema validation,
  static/template splitting, exit-code mapping, the disabled-tier
  refusal;
- megastep granularity plumbing: ``validate_unroll``,
  ``elastic.align_commit_every``, the ``elastic.run`` budget/stride
  validation, and the world-stamp exemption of the dispatch-only flag;
- the journal's synthesized per-step latency estimate (megastep bracket
  latency / unroll -> the ``megastep_step`` histogram).

The traced half (megastep == N eager steps bit-identity, MPX130 through
analyze/env=error, the elastic shrink drill, HLO identity at unroll=1)
is tests/test_megastep.py, which needs jax >= the package floor.
"""

import importlib
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_megastep_iso"


def _load_isolated():
    """Load the pure-Python megastep stack under a private package name
    (bypasses mpi4jax_tpu/__init__.py and its JAX floor; state isolated
    from any real import in the same process)."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "telemetry", "resilience", "aot",
                "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "analysis.report",
        "analysis.graph",
        "analysis.checkers",
        "telemetry.core",
        "telemetry.journal",
        "resilience.elastic",
        "aot.invalidation",
        "aot.fastpath",
        "aot.warm",
        "parallel.megastep",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = ISO.utils.config
report = ISO.analysis.report
graph_mod = ISO.analysis.graph
checkers = ISO.analysis.checkers
tcore = ISO.telemetry.core
journal = ISO.telemetry.journal
elastic = ISO.resilience.elastic
inv = ISO.aot.invalidation
fastpath = ISO.aot.fastpath
warm = ISO.aot.warm
megastep = ISO.parallel.megastep


# ---------------------------------------------------------------------------
# catalog + checker registry
# ---------------------------------------------------------------------------


def test_mpx130_in_catalog_and_registry():
    assert report.CODES["MPX130"].severity == report.ERROR
    assert "megastep" in report.CODES["MPX130"].title
    assert "MPX130" in checkers.registered_codes()


# ---------------------------------------------------------------------------
# MPX130: span straddles a megastep loop boundary
# ---------------------------------------------------------------------------


def _span_events(start_loop, wait_loop, span=7, include_wait=True):
    evts = [graph_mod.CollectiveEvent(
        index=0, op="allreduce_start", comm_uid=1, reduction="sum",
        dtype="float32", shape=(8,), span=span, loop=start_loop,
        unroll=4 if start_loop is not None else None)]
    if include_wait:
        evts.append(graph_mod.CollectiveEvent(
            index=1, op="allreduce_wait", comm_uid=1, reduction="sum",
            dtype="float32", shape=(8,), span=span, loop=wait_loop,
            unroll=4 if wait_loop is not None else None))
    return evts


def _findings(events):
    graph = graph_mod.CollectiveGraph(events=events, meta={"pinned": False})
    return checkers.check_megastep_span_straddle(graph)


def test_mpx130_clean_when_span_inside_one_iteration():
    assert not _findings(_span_events(start_loop=1, wait_loop=1))


def test_mpx130_clean_outside_any_loop():
    assert not _findings(_span_events(start_loop=None, wait_loop=None))


def test_mpx130_start_inside_wait_outside():
    findings = _findings(_span_events(start_loop=1, wait_loop=None))
    assert len(findings) == 1
    f = findings[0]
    assert f.code == "MPX130" and f.severity == "error"
    assert "straddles" in f.message
    assert "unroll" in f.suggestion


def test_mpx130_wait_inside_start_outside():
    findings = _findings(_span_events(start_loop=None, wait_loop=2))
    assert len(findings) == 1
    assert "start is not" in findings[0].message


def test_mpx130_spanning_two_different_loops():
    findings = _findings(_span_events(start_loop=1, wait_loop=2))
    assert len(findings) == 1 and findings[0].code == "MPX130"


def test_mpx130_unwaited_start_inside_loop():
    findings = _findings(
        _span_events(start_loop=3, wait_loop=None, include_wait=False))
    assert len(findings) == 1
    assert "*_wait" in findings[0].message


def test_mpx130_multiple_spans_report_separately():
    events = (_span_events(start_loop=1, wait_loop=None, span=1)
              + _span_events(start_loop=2, wait_loop=2, span=2))
    assert len(_findings(events)) == 1  # only span 1 straddles


# ---------------------------------------------------------------------------
# MPX128: loop-body events are exempt, advisory recommends unroll=
# ---------------------------------------------------------------------------


def _hot_events(n, loop=None):
    return [graph_mod.CollectiveEvent(
        index=i, op="allreduce", comm_uid=1, reduction="sum",
        dtype="float32", shape=(8,), loop=loop,
        unroll=None if loop is None else 8)
        for i in range(n)]


def test_mpx128_skips_megastep_loop_body_events():
    n = checkers.AOT_ADVISORY_MIN_REPEATS
    graph = graph_mod.CollectiveGraph(events=_hot_events(n, loop=5),
                                      meta={"pinned": False})
    assert not checkers.check_unpinned_hot_loop(graph)
    # the same stream outside any loop still fires
    graph = graph_mod.CollectiveGraph(events=_hot_events(n),
                                      meta={"pinned": False})
    assert checkers.check_unpinned_hot_loop(graph)


def test_mpx128_advisory_recommends_unroll():
    n = checkers.AOT_ADVISORY_MIN_REPEATS
    graph = graph_mod.CollectiveGraph(events=_hot_events(n),
                                      meta={"pinned": False})
    (finding,) = checkers.check_unpinned_hot_loop(graph)
    assert "unroll=" in finding.suggestion
    assert "megastep" in finding.suggestion


# ---------------------------------------------------------------------------
# the C++ fast-path installer (aot/fastpath.py)
# ---------------------------------------------------------------------------


class _FakeExe:
    def __init__(self, result="fastcall", raises=False):
        self.result = result
        self.raises = raises
        self.calls = []

    def create_cpp_call(self, no_kwargs, in_tree, out_tree):
        self.calls.append((no_kwargs, in_tree, out_tree))
        if self.raises:
            raise RuntimeError("jaxlib said no")
        if self.result == "fastcall":
            return lambda *a: ("fast", a)
        return self.result


class _FakeCompiled:
    def __init__(self, exe, in_tree="IT", out_tree="OT"):
        self._executable = exe
        if in_tree is not None:
            self.in_tree = in_tree
        if out_tree is not None:
            self.out_tree = out_tree

    def __call__(self, *a):
        return ("python", a)


def test_fastpath_installs_cpp_call():
    exe = _FakeExe()
    compiled = _FakeCompiled(exe)
    call, used = fastpath.cpp_call_for(compiled)
    assert used and call is not compiled
    assert call(1, 2) == ("fast", (1, 2))
    # the factory was asked for the positional-only (no_kwargs) form
    assert exe.calls == [(True, "IT", "OT")]
    assert fastpath.supported(compiled)


def test_fastpath_missing_executable_falls_back():
    class Bare:
        pass

    bare = Bare()
    call, used = fastpath.cpp_call_for(bare)
    assert call is bare and not used
    assert not fastpath.supported(bare)


def test_fastpath_missing_factory_falls_back():
    class Exe:
        pass

    compiled = _FakeCompiled(Exe())
    call, used = fastpath.cpp_call_for(compiled)
    assert call is compiled and not used


def test_fastpath_factory_raise_falls_back():
    compiled = _FakeCompiled(_FakeExe(raises=True))
    call, used = fastpath.cpp_call_for(compiled)
    assert call is compiled and not used
    assert call(3) == ("python", (3,))


def test_fastpath_non_callable_result_falls_back():
    compiled = _FakeCompiled(_FakeExe(result=None))
    call, used = fastpath.cpp_call_for(compiled)
    assert call is compiled and not used


def test_fastpath_missing_trees_falls_back_then_params():
    compiled = _FakeCompiled(_FakeExe(), in_tree=None, out_tree=None)
    call, used = fastpath.cpp_call_for(compiled)
    assert call is compiled and not used

    class Params:
        in_tree = "PIT"
        out_tree = "POT"

    exe = _FakeExe()
    older = _FakeCompiled(exe, in_tree=None, out_tree=None)
    older._params = Params()
    call, used = fastpath.cpp_call_for(older)
    assert used and exe.calls == [(True, "PIT", "POT")]


# ---------------------------------------------------------------------------
# cache-warming manifest (aot/warm.py)
# ---------------------------------------------------------------------------


def _manifest(**program_over):
    program = {
        "fn": "my.mod:step",
        "args": [{"shape": [8, 16], "dtype": "float32"}, {"static": 4}],
        "unroll": 8,
        "donate_argnums": [0],
    }
    program.update(program_over)
    return {"programs": [program]}


def test_parse_manifest_splits_statics_and_templates():
    (spec,) = warm.parse_manifest(_manifest())
    assert spec.fn == "my.mod:step"
    assert spec.import_path() == ("my.mod", "step")
    assert spec.static_argnums == (1,)
    assert spec.unroll == 8
    assert spec.donate_argnums == (0,)
    assert spec.args[0]["shape"] == [8, 16]


@pytest.mark.parametrize("bad, match", [
    ({"programs": []}, "non-empty"),
    ({"nope": 1}, "programs"),
    (_manifest(fn="no_colon"), "module.path:callable"),
    (_manifest(args=[{"shape": [4]}]), "dtype"),
    (_manifest(args=[{"static": 1, "shape": [4]}]), "mixes"),
    (_manifest(args=[{"shape": [-1], "dtype": "f32"}]), "non-negative"),
    (_manifest(unroll=0), "unroll"),
    (_manifest(donate_argnums="x"), "donate_argnums"),
    (_manifest(wrap="yes"), "wrap"),
])
def test_parse_manifest_rejects_malformed(bad, match):
    with pytest.raises(warm.ManifestError, match=match):
        warm.parse_manifest(bad)


def test_load_manifest_unreadable_and_invalid(tmp_path):
    with pytest.raises(warm.ManifestError, match="cannot read"):
        warm.load_manifest(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(warm.ManifestError, match="not valid JSON"):
        warm.load_manifest(str(bad))


def test_warm_refuses_without_cache_dir(monkeypatch, tmp_path):
    monkeypatch.delenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", raising=False)
    code, payload = warm.warm_from_manifest(str(tmp_path / "m.json"))
    assert code == warm.EXIT_BAD_MANIFEST
    assert "COMPILE_CACHE_DIR" in payload["error"]


def test_warm_bad_manifest_exit_code(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    code, payload = warm.warm_from_manifest(str(tmp_path / "missing.json"))
    assert code == warm.EXIT_BAD_MANIFEST
    assert "error" in payload


def test_warm_failed_import_exit_code(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    path = tmp_path / "m.json"
    import json

    path.write_text(json.dumps({"programs": [{
        "fn": "definitely_not_a_module_xyz:step",
        "args": [{"shape": [4], "dtype": "float32"}],
    }]}))
    code, payload = warm.warm_from_manifest(str(path))
    assert code == warm.EXIT_FAILED
    assert payload["failed"] == 1 and payload["warmed"] == 0
    assert payload["failures"][0]["fn"].startswith("definitely_not")


# ---------------------------------------------------------------------------
# megastep granularity plumbing
# ---------------------------------------------------------------------------


def test_validate_unroll():
    assert megastep.validate_unroll(1) == 1
    assert megastep.validate_unroll(64) == 64
    with pytest.raises(ValueError, match=">= 1"):
        megastep.validate_unroll(0)
    with pytest.raises(TypeError, match="positive integer"):
        megastep.validate_unroll(None)
    assert not megastep.tracing_megastep()


def test_align_commit_every():
    assert elastic.align_commit_every(1, 8) == 8
    assert elastic.align_commit_every(8, 8) == 8
    assert elastic.align_commit_every(9, 8) == 16
    assert elastic.align_commit_every(5, 1) == 5
    assert elastic.align_commit_every(3, 4) == 4


def test_elastic_run_rejects_misaligned_budget():
    class MegaStep:
        unroll = 8

        def __call__(self, state, step, comm):  # pragma: no cover
            return state

    # the validation fires before any store/watchdog touch, so a bare
    # None store is fine — the point is the error, not the loop
    with pytest.raises(ValueError, match="multiple of the step function"):
        elastic.run(MegaStep(), None, None, steps=10)


def test_dispatch_only_flag_never_stales_pins(monkeypatch):
    ws = inv.WorldStamp.capture()
    monkeypatch.setenv("MPI4JAX_TPU_CPP_DISPATCH", "false")
    assert ws.is_current()
    ws.check()  # no raise
    for name in inv.DISPATCH_ONLY_FLAGS:
        assert name in config.FLAGS  # exemption list stays declared


def test_unroll_default_flag_stales_pins(monkeypatch):
    ws = inv.WorldStamp.capture()
    monkeypatch.setenv("MPI4JAX_TPU_UNROLL_DEFAULT", "8")
    # the default unroll SHAPES traces: moving it must revoke pins
    assert not ws.is_current()


def test_new_flags_declared_and_parsed(monkeypatch):
    assert "MPI4JAX_TPU_UNROLL_DEFAULT" in config.FLAGS
    assert "MPI4JAX_TPU_CPP_DISPATCH" in config.FLAGS
    assert config.unroll_default() == 1
    monkeypatch.setenv("MPI4JAX_TPU_UNROLL_DEFAULT", "16")
    assert config.unroll_default() == 16
    monkeypatch.setenv("MPI4JAX_TPU_UNROLL_DEFAULT", "0")
    with pytest.raises(ValueError):
        config.unroll_default()
    monkeypatch.delenv("MPI4JAX_TPU_UNROLL_DEFAULT")
    assert config.cpp_dispatch() is True
    monkeypatch.setenv("MPI4JAX_TPU_CPP_DISPATCH", "false")
    assert config.cpp_dispatch() is False


# ---------------------------------------------------------------------------
# the journal's synthesized per-step estimate
# ---------------------------------------------------------------------------


def test_journal_megastep_per_step_estimate(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_TELEMETRY_DIR", raising=False)
    tcore.reset()
    try:
        meta = {"op": "megastep", "unroll": 8, "comm_uid": "3",
                "axes": ["x"], "bytes": 0, "dtype": ""}
        journal.begin("cafecafe", 0, meta)
        journal.end("cafecafe", 0, {"algo": "loop"})
        snap = tcore.snapshot()
        mega_key = tcore.op_key("megastep", "3", "loop", "")
        step_key = tcore.op_key("megastep_step", "3", "estimate", "")
        assert "latency" in snap["ops"][mega_key]
        step_hist = snap["ops"][step_key]["latency"]
        assert step_hist["count"] == 1
        # the estimate is bracket latency / unroll
        mega_hist = snap["ops"][mega_key]["latency"]
        assert step_hist["sum"] == pytest.approx(mega_hist["sum"] / 8)
    finally:
        tcore.reset()


def test_journal_single_step_records_no_estimate():
    tcore.reset()
    try:
        journal.begin("beefbeef", 0, {"op": "megastep", "unroll": 1,
                                      "comm_uid": "3"})
        journal.end("beefbeef", 0, {})
        step_key = tcore.op_key("megastep_step", "3", "estimate", "")
        assert step_key not in tcore.snapshot()["ops"]
    finally:
        tcore.reset()
