"""AOT-pinned serving step: the cold-start + hot-loop walkthrough.

A tensor-parallel decode-style step (row-parallel matmul -> partial-sum
allreduce -> activation), pinned once with ``mpx.compile`` and executed
as a compiled artifact — the serving pattern where BOTH costs the AOT
layer removes actually bite:

- **cold start**: with ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` set, the first
  process compiles and serializes; every later cold start (and every
  rank of a multi-host job) deserializes instead of re-lowering —
  ``pin_wall_s`` collapses and ``disk_cache.hits`` goes positive;
- **hot loop**: the pinned call path does no env-flag parsing, no
  cache-key hashing, and no program-cache lookups — ``per_call_us`` is
  the serving-loop floor.

Run it twice with a shared cache dir and compare the JSON lines::

    export MPI4JAX_TPU_COMPILE_CACHE_DIR=/tmp/mpx-compile-cache
    python examples/aot_serving_step.py   # cold: compiles + writes
    python examples/aot_serving_step.py   # warm: deserializes (hits > 0)

(The CI aot lane runs exactly this drill on the 8-device CPU mesh and
asserts the second run loads from disk and pins faster.)  docs/aot.md
is the full story.

Run: python examples/aot_serving_step.py [--steps N] [--dim D] [--json]
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def decode_step(x, w):
    """The per-rank decode step: a row-parallel linear — each rank holds
    a (dim/size, dim) weight shard and its slice of the activations; the
    matmul produces a PARTIAL sum that one allreduce completes
    (Megatron-style).  Module-level so the cache-warming CLI can name it
    in a manifest (``python -m mpi4jax_tpu.aot warm``, docs/aot.md
    "Cache warming"): the output slice width comes from the weight
    shard's own shape, no closed-over configuration."""
    partial = x @ w
    full, _ = mpx.allreduce(partial, op=mpx.SUM)
    return jnp.tanh(mpx.varying(full))[:, : w.shape[0]]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50,
                   help="pinned hot-loop calls to time")
    p.add_argument("--dim", type=int, default=256,
                   help="model dimension (split over ranks)")
    p.add_argument("--json", action="store_true",
                   help="print ONLY the JSON result line")
    args = p.parse_args()

    comm = mpx.get_default_comm()
    size = comm.Get_size()
    dim = max(size, args.dim // size * size)  # divisible by the mesh

    # global arrays: leading axis = ranks
    x = jnp.ones((size, 8, dim // size), jnp.float32) * 0.01
    w = jnp.ones((size, dim // size, dim), jnp.float32) * 0.01

    t0 = time.perf_counter()
    pinned = mpx.compile(decode_step, x, w, comm=comm)
    pin_wall = time.perf_counter() - t0

    # hot loop: the pinned artifact, no per-call key work
    out = pinned(x, w)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = pinned(x, w)
    jax.block_until_ready(out)
    per_call = (time.perf_counter() - t0) / args.steps

    stats = mpx.cache_stats()
    result = {
        "workload": f"tp-decode dim={dim} over {size} ranks",
        "pin_wall_s": round(pin_wall, 4),
        "steps": args.steps,
        "per_call_us": round(per_call * 1e6, 2),
        "from_disk": pinned.from_disk,
        "aot": stats["aot"],
        "disk_cache": {
            k: stats["disk_cache"][k]
            for k in ("enabled", "hits", "misses", "writes", "evictions",
                      "bytes", "entries")
        },
    }
    if not args.json:
        src = "deserialized from the persistent cache" if pinned.from_disk \
            else "compiled fresh"
        print(f"pinned in {pin_wall:.3f}s ({src}); "
              f"{args.steps} calls at {per_call * 1e6:.1f} us/call")
        if not stats["disk_cache"]["enabled"]:
            print("hint: set MPI4JAX_TPU_COMPILE_CACHE_DIR and run twice "
                  "to see the cold-start cache in action")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
