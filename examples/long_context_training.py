"""Training with sequence parallelism: ring attention × data parallel.

The composition a long-context training run actually uses, end-to-end on
one 2-D mesh ``("dp", "sp")``:

- activations are sharded over BOTH axes: batch over ``dp``, sequence over
  ``sp`` (each rank holds a (B_local, T_local, ...) tile);
- attention runs over the ``sp`` sub-communicator via
  ``mpi4jax_tpu.attention.ring_attention`` — exact causal attention over
  the full sequence with O(T/n) memory per chip, forward and backward
  (the memory-efficient custom VJP re-rotates K/V);
- parameters are replicated; each rank's parameter gradient is partial
  (it saw a batch/sequence tile), so one ``allreduce`` over the WORLD
  communicator completes it — the reference's DP-SGD pattern
  (ref tests/collective_ops/test_allreduce.py:254-324) extended with a
  sequence axis;
- the optimizer step is plain JAX on the replicated params.

The model is a minimal pre-LN transformer block + readout trained to
regress a target sequence.  ``tests/test_examples.py`` pins the
distributed step's loss and every parameter gradient against a
single-device reference on the gathered data; ``main()`` additionally
asserts the loss decreases over five steps.
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.attention import ring_attention  # noqa: E402


def init_params(key, d_model, d_ff):
    ks = jax.random.split(key, 5)
    s = 1.0 / jnp.sqrt(d_model)
    return {
        "wqkv": jax.random.normal(ks[0], (d_model, 3 * d_model)) * s,
        "wo": jax.random.normal(ks[1], (d_model, d_model)) * s,
        "w1": jax.random.normal(ks[2], (d_model, d_ff)) * s,
        "w2": jax.random.normal(ks[3], (d_ff, d_model)) * (1.0 / jnp.sqrt(d_ff)),
        "wout": jax.random.normal(ks[4], (d_model, 1)) * s,
    }


def _ln(x):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6)


def block_forward(params, x, *, heads, attend):
    """Pre-LN transformer block + scalar readout.

    ``x``: (B, T, D_model) — T may be a rank-local sequence shard; the
    attention implementation is injected via ``attend`` so the SAME
    function serves the sharded model (ring attention over the sp comm)
    and the single-device reference (full attention).
    """
    b, t, d = x.shape
    h = heads
    qkv = _ln(x) @ params["wqkv"]
    q, k, v = (y.reshape(b, t, h, d // h) for y in jnp.split(qkv, 3, -1))
    att = attend(q, k, v).reshape(b, t, d)
    x = x + att @ params["wo"]
    x = x + jax.nn.gelu(_ln(x) @ params["w1"]) @ params["w2"]
    return (x @ params["wout"])[..., 0]  # (B, T)


def make_train_step(world, sp, heads, lr=1e-2):
    """One SGD step on ``world``'s mesh: activations sharded (dp, sp),
    params replicated, gradient completed by a world allreduce."""

    def local_loss(params, x, y):
        pred = block_forward(
            params, x, heads=heads,
            attend=lambda q, k, v: ring_attention(
                q, k, v, comm=sp, causal=True
            ),
        )
        # rank-local partial of the GLOBAL mean squared error: divide by
        # the global element count so the summed (allreduced) loss and
        # gradients are means — without this, gradient magnitude scales
        # with world size x tile size and SGD diverges
        denom = world.Get_size() * y.size
        return jnp.sum((pred - y) ** 2) / denom

    @mpx.spmd(comm=world)
    def step(params, x, y):
        local, grads = jax.value_and_grad(local_loss)(params, x, y)
        # the fusion-friendly idiom (docs/overlap.md): issue the loss +
        # every per-leaf gradient allreduce first, consume after — under
        # MPI4JAX_TPU_FUSION=auto the adjacent run coalesces into one
        # flat-buffer collective; with fusion off it runs call by call,
        # same math either way
        loss, tok = mpx.allreduce(local, op=mpx.SUM, comm=world)
        red = {}
        for name in sorted(grads):
            red[name], tok = mpx.allreduce(grads[name], op=mpx.SUM,
                                           comm=world, token=tok)
        out = {name: params[name] - lr * red[name] for name in red}
        return out, mpx.varying(loss, comm=world)

    return step


def main():
    n = len(jax.devices())
    n_dp = 2 if n % 2 == 0 and n > 1 else 1
    n_sp = n // n_dp
    mesh = mpx.make_world_mesh((n_dp, n_sp), ("dp", "sp"))
    world = mpx.Comm(("dp", "sp"), mesh=mesh)
    sp = world.sub("sp")

    b_loc, t_loc, d_model, d_ff, heads = 2, 32, 32, 64, 4
    params = init_params(jax.random.PRNGKey(0), d_model, d_ff)
    # replicate params per rank (leading world axis)
    params_g = {k: jnp.broadcast_to(v, (n, *v.shape))
                for k, v in params.items()}
    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(kx, (n, b_loc, t_loc, d_model), jnp.float32)
    y = jax.random.normal(ky, (n, b_loc, t_loc), jnp.float32)

    step = make_train_step(world, sp, heads, lr=0.1)
    losses = []
    # fuse the adjacent gradient allreduces into one flat-buffer
    # collective per step (docs/overlap.md); reset below so the demo
    # leaves no global state behind
    mpx.set_fusion_mode("auto")
    try:
        for i in range(5):
            params_g, loss = step(params_g, x, y)
            losses.append(float(jnp.asarray(loss)[0]))
    finally:
        mpx.set_fusion_mode(None)
    print(f"dp={n_dp} x sp={n_sp}: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"over {len(losses)} steps")
    assert losses[-1] < losses[0], "training did not reduce the loss"


if __name__ == "__main__":
    main()
