"""Seeded interleave-cycle deadlock — INTENTIONALLY BROKEN (MPX121).

A hand-rolled interleaved pipeline boundary gone wrong: every rank
ships its two virtual stage-chunks around the wrap ring, but EVEN ranks
move chunk 0 first and ODD ranks move chunk 1 first (a ``lax.cond`` on
rank parity where both branches communicate, so the per-trace checkers
stay silent).  Each rank's schedule is individually well-formed —
send-before-recv, tags matched, tokens threaded — yet across ranks the
chunk-0 receive of an even rank waits on its odd neighbor's SECOND
send, which sits behind that rank's chunk-1 receive, which waits on an
even rank's second send, ... around the ring: a wait-for cycle that
deadlocks under any buffering.  This is exactly the cycle class the
``mpx.pipeline`` schedule compiler can never emit (one fixed chunk
order per tick on every rank — docs/pipeline.md "Interleaved virtual
stages"); hand-rolled interleaving is how you get it.

Only the cross-rank schedule pass catches it, by re-tracing once per
rank and walking the wait-for graph (MPX121; a variant mixing a
collective into the cycle surfaces as MPX122):

    python examples/broken/pipeline_interleave_deadlock.py

runs both front-ends — ``mpx.analyze(ranks='all')`` and the ambient
``MPI4JAX_TPU_ANALYZE=error`` path — and asserts both flag the cycle.
This file lives under ``examples/broken/`` so the CI sweep over
``examples/*.py`` (which must come back clean) does not pick it up; the
pipeline CI lane instead asserts that analyzing THIS file fails with
MPX121 (.github/workflows/test.yml).
"""

import os
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def build_boundary(comm):
    """One interleave boundary tick over the wrap ring, chunk order
    rank-divergent: even ranks ship chunk 0 (tag 0) then chunk 1
    (tag 1), odd ranks the reverse."""
    n = comm.Get_size()
    ring = tuple((i, (i + 1) % n) for i in range(n))

    def boundary(h):
        r = comm.Get_rank()

        def even_path(v):
            t = mpx.send(v, ring, tag=0, comm=comm)
            c0, t = mpx.recv(v, source=ring, tag=0, comm=comm, token=t)
            t = mpx.send(c0, ring, tag=1, comm=comm, token=t)
            c1, _t = mpx.recv(c0, source=ring, tag=1, comm=comm, token=t)
            return c1

        def odd_path(v):
            t = mpx.send(v, ring, tag=1, comm=comm)
            c1, t = mpx.recv(v, source=ring, tag=1, comm=comm, token=t)
            t = mpx.send(c1, ring, tag=0, comm=comm, token=t)
            c0, _t = mpx.recv(c1, source=ring, tag=0, comm=comm, token=t)
            return c0

        return lax.cond(r % 2 == 0, even_path, odd_path, h)

    return boundary


def main():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    if n < 2 or n % 2:
        print("needs an even rank count >= 2 (e.g. XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); the parity "
              "cycle needs both branches populated")
        return
    boundary = build_boundary(comm)
    x = jnp.stack([jnp.full((16,), float(r)) for r in range(n)])

    # --- front-end 1: explicit cross-rank analysis
    report = mpx.analyze(boundary, x, comm=comm, ranks="all")
    print(report.render(), file=sys.stderr)
    codes = {f.code for f in report.findings}
    assert codes & {"MPX121", "MPX122"}, \
        f"expected MPX121/MPX122, got {sorted(codes)}"
    print("mpx.analyze(ranks='all'): interleave cycle caught (MPX121)",
          file=sys.stderr)

    # --- front-end 2: the ambient env=error path
    mpx.set_analyze_mode("error")
    try:
        try:
            mpx.run(boundary, x, comm=comm)
        except mpx.AnalysisError as e:
            assert any(f.code in ("MPX121", "MPX122")
                       for f in e.findings), e.findings
            print("MPI4JAX_TPU_ANALYZE=error: interleave cycle caught "
                  "at trace time", file=sys.stderr)
        else:
            raise AssertionError("ambient cross-rank pass missed the "
                                 "interleave cycle")
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


if __name__ == "__main__":
    main()
