"""Seeded async-span donation race — INTENTIONALLY BROKEN (MPX139).

An ``allreduce_start`` puts its input buffer on the wire: the chunked
ring phases keep reading it until the matching ``allreduce_wait``.
Handing that buffer's storage to a pinned executable in the gap —
``mpx.compile(..., donate_argnums=(0,))`` donates the argument so XLA
may overwrite it in place — is a write-after-start race: the wire can
ship the scaled bytes instead of the originals, silently corrupting the
reduction on every rank.

Nothing structural is wrong with the schedule (start and wait pair up,
tokens chain, the cross-rank matcher is happy), so only the dataflow
hazard verifier catches it, by joining the recorded span intervals with
the pinner's donation records (docs/analysis.md "Dataflow hazards"):

    python examples/broken/overlap_donation_race.py

runs both front-ends — ``mpx.analyze`` and the ambient
``MPI4JAX_TPU_ANALYZE=error`` path — and asserts both flag MPX139.  This
file lives under ``examples/broken/`` so the CI sweep over
``examples/*.py`` (which must come back clean) does not pick it up; the
CI analyze lane instead asserts that analyzing THIS file fails with
MPX139 (.github/workflows/test.yml).
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def build_step(comm):
    """One training-ish step: overlap a gradient allreduce with a pinned
    parameter rescale... that donates the gradient buffer mid-span."""
    local = jax.ShapeDtypeStruct((16,), jnp.float32)
    # the donating pinned helper (eager convention: no region of its own)
    scale = mpx.compile(lambda v: v * 2.0, local, wrap=False,
                        donate_argnums=(0,))

    def step(x):
        handle, t = mpx.allreduce_start(x, mpx.SUM, comm=comm)
        # BUG: x is still held by the open span — donating its storage
        # here lets the executable overwrite bytes the ring phases are
        # about to ship.  The fix is to call scale() after the wait (or
        # on a copy).
        y = scale(x)
        total, t = mpx.allreduce_wait(handle, token=t)
        return total + y

    return step


def main():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    if n < 2:
        print("needs >= 2 devices (e.g. XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing races "
              "on 1 rank")
        return
    x = jnp.stack([jnp.full((16,), float(r)) for r in range(n)])

    # --- front-end 1: explicit analysis
    step = build_step(comm)
    report = mpx.analyze(step, x, comm=comm)
    print(report.render(), file=sys.stderr)
    codes = {f.code for f in report.findings}
    assert "MPX139" in codes, f"expected MPX139, got {sorted(codes)}"
    print("mpx.analyze: donation race caught (MPX139)", file=sys.stderr)

    # --- front-end 2: the ambient env=error path (the armed region
    # recorder sees the same span + donation records at trace time)
    mpx.set_analyze_mode("error")
    try:
        # re-pin under the new mode epoch: flipping the analyze mode
        # (correctly) stales programs pinned before it
        step2 = build_step(comm)
        try:
            mpx.run(step2, x, comm=comm)
        except mpx.AnalysisError as e:
            assert any(f.code == "MPX139" for f in e.findings), e.findings
            print("MPI4JAX_TPU_ANALYZE=error: donation race caught "
                  "(MPX139) at trace time", file=sys.stderr)
        else:
            raise AssertionError("ambient pass missed the donation race")
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


if __name__ == "__main__":
    main()
