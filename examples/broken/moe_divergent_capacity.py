"""Seeded rank-divergent MoE capacity split — INTENTIONALLY BROKEN
(MPX120).

The MoE dispatch/combine contract (docs/moe.md, parallel/moe.py) is
that every rank derives the SAME capacity bucketing from shared static
structure: the dispatch buffer shape and the combine chunk count are
part of the collective schedule.  This fixture breaks it the way real
MoE stacks do — by deriving the capacity-chunk granularity from the
rank: even ranks split their combine into TWO half-capacity alltoalls,
odd ranks issue ONE full-capacity exchange.  Both branches of the
``lax.cond`` communicate (so MPX108 stays silent) and every branch's
output shape matches, but at the second collective position on the comm
the even ranks sit in an ``alltoall`` while the odd ranks are already
in the gate-stats ``allreduce`` — a cross-rank order mismatch that
hangs at run time.

Only the cross-rank schedule pass catches it, by re-tracing once per
rank (concretizing ``comm.Get_rank`` so the cond takes its real
per-rank path) and matching the per-rank schedules position by
position (docs/analysis.md "Cross-rank verification"):

    python examples/broken/moe_divergent_capacity.py

runs both front-ends — ``mpx.analyze(ranks='all')`` and the ambient
``MPI4JAX_TPU_ANALYZE=error`` path — and asserts both flag MPX120.
This file lives under ``examples/broken/`` so the CI sweep over
``examples/*.py`` (which must come back clean) does not pick it up; the
CI analyze lane instead asserts that analyzing THIS file fails with
MPX120 (.github/workflows/test.yml) — alltoall traffic is the pattern
the MPX120-125 machinery had never been stress-tested on.
"""

import os
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mpi4jax_tpu as mpx  # noqa: E402

CAPACITY = 4
D = 8


def build_combine(comm):
    """The combine stage with rank-derived chunking: even ranks exchange
    two half-capacity buckets, odd ranks one full bucket, then everyone
    allreduces the gate load stats.  The schedules disagree at the
    second collective position on the comm."""

    def combine(buckets):
        # buckets: (k, CAPACITY, D) — this rank's processed expert output
        r = comm.Get_rank()

        def even_path(b):
            half = CAPACITY // 2
            lo, _ = mpx.alltoall(b[:, :half], comm=comm)
            hi, _ = mpx.alltoall(b[:, half:], comm=comm)
            return jnp.concatenate([lo, hi], axis=1)

        def odd_path(b):
            out, _ = mpx.alltoall(b, comm=comm)
            return out

        combined = lax.cond(r % 2 == 0, even_path, odd_path, buckets)
        load, _ = mpx.allreduce(jnp.sum(combined), op=mpx.SUM, comm=comm)
        return combined, load

    return combine


def main():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    if n < 2:
        print("needs >= 2 devices (e.g. XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing to "
              "diverge on 1 rank")
        return
    combine = build_combine(comm)
    x = jnp.stack([
        jnp.full((n, CAPACITY, D), float(r)) for r in range(n)
    ])

    # --- front-end 1: explicit cross-rank analysis
    report = mpx.analyze(combine, x, comm=comm, ranks="all")
    print(report.render(), file=sys.stderr)
    codes = {f.code for f in report.findings}
    assert "MPX120" in codes, f"expected MPX120, got {sorted(codes)}"
    print("mpx.analyze(ranks='all'): rank-divergent capacity split "
          "caught (MPX120)", file=sys.stderr)

    # --- front-end 2: the ambient env=error path (the cross-rank pass
    # runs at spmd trace time, before anything compiles)
    mpx.set_analyze_mode("error")
    try:
        try:
            mpx.run(combine, x, comm=comm)
        except mpx.AnalysisError as e:
            assert any(f.code == "MPX120" for f in e.findings), e.findings
            print("MPI4JAX_TPU_ANALYZE=error: rank-divergent capacity "
                  "split caught (MPX120) at trace time", file=sys.stderr)
        else:
            raise AssertionError("ambient cross-rank pass missed the "
                                 "divergent capacity split")
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


if __name__ == "__main__":
    main()
