"""Seeded EF-residual schedule gate — INTENTIONALLY BROKEN (MPX141).

The error-feedback residual (``mpx.compress.ef_allreduce``) is the one
value in a compressed training step that is rank-local *by design*: each
rank accumulates its own quantization error.  Gating control flow on it
is therefore gating on a value that differs across ranks — and when the
gated branches issue *different* collective schedules, the program
deadlocks the first step the residuals disagree: some ranks take the
two-collective resync path while the rest take the one-collective path,
and the second reduce waits forever.

MPX108 (branches disagree about communicating at all) stays silent here
— BOTH branches communicate.  The per-rank cross-rank re-trace cannot
concretize the predicate either (it is traced data, not a rank id).
Only the dataflow taint pass sees it, by following the rank-local
lineage from the residual into the predicate and comparing the branch
schedules (docs/analysis.md "Dataflow hazards"):

    python examples/broken/ef_divergent_gate.py

runs both front-ends — ``mpx.analyze`` and the ambient
``MPI4JAX_TPU_ANALYZE=error`` path — and asserts both flag MPX141 (the
MPX142 approximate-lineage advisory rides along: the same predicate also
carries wire-codec error).  This file lives under ``examples/broken/``
so the CI sweep over ``examples/*.py`` (which must come back clean) does
not pick it up; the CI analyze lane instead asserts that analyzing THIS
file fails with MPX141 (.github/workflows/test.yml).
"""

import os
import sys

# a lossy wire codec makes the residual real (and arms the verifier's
# approximate-lineage seeds); the rank-local hazard is structural either
# way
os.environ.setdefault("MPI4JAX_TPU_COMPRESS", "bf16")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def build_step(comm):
    def step(g, res):
        total, new_res, _ = mpx.compress.ef_allreduce(g, res, comm=comm)
        # BUG: drift is derived from the rank-LOCAL residual — every rank
        # computes a different value.  Replicate it first
        # (allreduce/pmax) if it must steer the schedule.
        drift = jnp.max(jnp.abs(new_res))

        def resync(v):
            # two collectives: re-reduce, then re-center
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            m, _ = mpx.allreduce(jnp.mean(s) * jnp.ones_like(s),
                                 mpx.SUM, comm=comm)
            return s - m / jnp.float32(comm.Get_size())

        def keep(v):
            # one collective: both branches communicate, so MPX108 stays
            # silent — but the SCHEDULES differ, which is the hang
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            return s

        return lax.cond(drift > jnp.float32(0.05), resync, keep, total), \
            new_res

    return step


def main():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    if n < 2:
        print("needs >= 2 devices (e.g. XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing "
              "diverges on 1 rank")
        return
    g = jnp.stack([jnp.full((64,), 1.0 + r) for r in range(n)])
    res = jnp.zeros_like(g)

    # --- front-end 1: explicit analysis (single trace: the taint pass
    # reads the rank-varying type the shard_map region gives the
    # residual)
    step = build_step(comm)
    report = mpx.analyze(step, g, res, comm=comm)
    print(report.render(), file=sys.stderr)
    codes = {f.code for f in report.findings}
    assert "MPX141" in codes, f"expected MPX141, got {sorted(codes)}"
    print("mpx.analyze: rank-local schedule gate caught (MPX141)",
          file=sys.stderr)

    # --- front-end 2: the ambient env=error path (the cross-rank region
    # pass runs the same taint pass per rank at trace time)
    mpx.set_analyze_mode("error")
    try:
        try:
            mpx.run(step, g, res, comm=comm)
        except mpx.AnalysisError as e:
            assert any(f.code == "MPX141" for f in e.findings), e.findings
            print("MPI4JAX_TPU_ANALYZE=error: rank-local schedule gate "
                  "caught (MPX141) at trace time", file=sys.stderr)
        else:
            raise AssertionError("ambient pass missed the divergent gate")
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


if __name__ == "__main__":
    main()
