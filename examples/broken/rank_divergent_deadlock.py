"""Seeded rank-divergent deadlock — INTENTIONALLY BROKEN (MPX121).

The classic cross-rank hang the per-trace verifier cannot see: a
``lax.cond`` on rank parity where BOTH branches communicate (so MPX108
stays silent), but even and odd ranks issue their point-to-point ops in
cycle-forming order — every rank posts its *receive* first, and the
matching send lives after the peer's receive.  Each even/odd pair is a
two-rank wait cycle: a guaranteed hang under any buffering.

Only the cross-rank schedule pass catches it, by re-tracing once per
rank (concretizing ``comm.Get_rank`` so the cond takes its real
per-rank path), matching the per-rank schedules, and walking the
wait-for graph (docs/analysis.md "Cross-rank verification"):

    python examples/broken/rank_divergent_deadlock.py

runs both front-ends — ``mpx.analyze(ranks='all')`` and the ambient
``MPI4JAX_TPU_ANALYZE=error`` path — and asserts both flag MPX121.  This
file lives under ``examples/broken/`` so the CI sweep over
``examples/*.py`` (which must come back clean) does not pick it up; the
CI analyze lane instead asserts that analyzing THIS file fails with
MPX121 (.github/workflows/test.yml).
"""

import os
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def build_exchange(comm):
    """Neighbor exchange over even/odd pairs, recv-before-send on BOTH
    sides — the textbook head-to-head deadlock, written rank-divergently."""
    k = comm.Get_size()
    up = tuple((i, i + 1) for i in range(0, k - 1, 2))    # even -> odd
    down = tuple((i + 1, i) for i in range(0, k - 1, 2))  # odd -> even

    def exchange(x):
        r = comm.Get_rank()

        def even_path(v):
            # wait for the odd neighbor's message... which it only sends
            # after ITS recv completes: a two-rank cycle
            got, t = mpx.recv(v, source=down, tag=0, comm=comm)
            mpx.send(v, up, tag=1, comm=comm, token=t)
            return got

        def odd_path(v):
            got, t = mpx.recv(v, source=up, tag=1, comm=comm)
            mpx.send(v, down, tag=0, comm=comm, token=t)
            return got

        return lax.cond(r % 2 == 0, even_path, odd_path, x)

    return exchange


def main():
    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    if n < 2:
        print("needs >= 2 devices (e.g. XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing to "
              "deadlock on 1 rank")
        return
    exchange = build_exchange(comm)
    x = jnp.stack([jnp.full((16,), float(r)) for r in range(n)])

    # --- front-end 1: explicit cross-rank analysis
    report = mpx.analyze(exchange, x, comm=comm, ranks="all")
    print(report.render(), file=sys.stderr)
    codes = {f.code for f in report.findings}
    assert "MPX121" in codes, f"expected MPX121, got {sorted(codes)}"
    print("mpx.analyze(ranks='all'): deadlock cycle caught (MPX121)",
          file=sys.stderr)

    # --- front-end 2: the ambient env=error path (the cross-rank pass
    # runs at spmd trace time, before anything compiles)
    mpx.set_analyze_mode("error")
    try:
        try:
            mpx.run(exchange, x, comm=comm)
        except mpx.AnalysisError as e:
            assert any(f.code == "MPX121" for f in e.findings), e.findings
            print("MPI4JAX_TPU_ANALYZE=error: deadlock cycle caught "
                  "(MPX121) at trace time", file=sys.stderr)
        else:
            raise AssertionError("ambient cross-rank pass missed the "
                                 "deadlock")
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


if __name__ == "__main__":
    main()
