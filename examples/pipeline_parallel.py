"""Pipeline parallelism: the naive ladder vs the compiled schedules.

Eight pipeline stages, one per rank, a 16-substage model (two substages
per rank — so the interleaved schedule has real virtual stages to own).
FIVE variants of the SAME forward pass, every one asserted BIT-IDENTICAL
to the sequential single-device reference:

- the **naive ladder** — the whole batch crawls stage to stage over
  matched ``send``/``recv`` pairs; the S-1 hops serialize end to end.
  This is the seeded positive for the cost model's **MPX135** advisory
  (serialized point-to-point chain on the critical path), whose text now
  cites the modeled bubble fraction of the ladder and the 1F1B price
  ``mpx.pipeline`` would get::

      python -m mpi4jax_tpu.analysis --ranks 8 --cost \
          examples/pipeline_parallel.py

  reports MPX135 (advisory — exit code stays 0);

- ``mpx.pipeline(..., schedule='gpipe')`` — the GPipe wavefront: M
  microbatches injected one per tick, every stage boundary shipping
  simultaneously over a blocking ``sendrecv`` shift;

- ``mpx.pipeline(..., schedule='1f1b')`` — same wavefront, but the
  boundary runs through the async point-to-point primitives
  (``send_start``/``recv_start``/``p2p_wait``) so the transfer overlaps
  the tick's compute, and the steady-state window compiles into ONE
  megastep ``fori_loop`` dispatch;

- ``mpx.pipeline(..., schedule='interleaved', virtual=2)`` — Megatron
  interleaved virtual stages: rank r owns substages r and 8+r, the
  boundary is a ring, and the pipeline fill shrinks by the chunk count;

- ``mpx.pipeline(...)`` with the default ``schedule='auto'`` — the cost
  model prices every expressible schedule (tuned alpha/beta when a
  tuning file is loaded) and runs the argmin.

The schedule math, the activation-stash bound, and when NOT to pipeline
live in docs/pipeline.md; the deliberately deadlocked interleave twin is
examples/broken/pipeline_interleave_deadlock.py (MPX121).

Run: python examples/pipeline_parallel.py   (8 devices, e.g.
     XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

import os
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.parallel.pipeline import split_microbatches  # noqa: E402

MICROBATCHES = 16
BATCH, DIM = 32, 8


def substage(h, w):
    """One model substage: a linear layer + nonlinearity."""
    return jnp.tanh(h @ w)


def stage_pair(h, w2):
    """One PIPELINE stage under the flat (virtual=1) schedules: the two
    consecutive substages rank r owns (``w2`` is ``(2, DIM, DIM)``)."""
    return substage(substage(h, w2[0]), w2[1])


def make_ladder(comm):
    """The naive ladder over ``comm`` (one stage per rank): compute,
    ship the whole activation to the next stage, wait, repeat — S-1
    serialized hops (MPX135).  Inputs are global arrays (leading axis =
    ranks): ``x[0]`` holds the real minibatch, ``w2s[r]`` rank r's
    substage pair; the result lives on the LAST stage's row."""
    stages = comm.Get_size()

    @mpx.spmd(comm=comm)
    def ladder(x, w2):
        rank = comm.Get_rank()
        h = stage_pair(x, w2)  # stage 0's lane holds the real value
        tok = None
        for s in range(1, stages):
            tok = mpx.send(h, dest={s - 1: s}, tag=s, token=tok)
            got, tok = mpx.recv(h, source={s - 1: s}, tag=s, token=tok)
            h = jnp.where(rank == s, stage_pair(got, w2), h)
        return h

    return ladder


def reference(x0, ws16):
    """Sequential single-device reference: all 16 substages, applied
    per-microbatch so every variant (which computes on microbatch-sized
    slices) can be pinned BIT-identical, not just allclose."""
    mbs = split_microbatches(x0, MICROBATCHES)
    outs = []
    for m in range(MICROBATCHES):
        h = mbs[m]
        for k in range(ws16.shape[0]):
            h = substage(h, ws16[k])
        outs.append(h)
    return jnp.concatenate(outs)


def main():
    comm = mpx.get_default_comm()
    stages = comm.Get_size()
    assert BATCH % MICROBATCHES == 0
    mb = BATCH // MICROBATCHES
    rng = np.random.default_rng(0)

    x0 = jnp.asarray(rng.normal(size=(BATCH, DIM)), jnp.float32)
    ws16 = jnp.asarray(rng.normal(size=(2 * stages, DIM, DIM)) * 0.5,
                       jnp.float32)
    # rank r's substage pair under the flat schedules...
    w2s = ws16.reshape(stages, 2, DIM, DIM)
    # ...and its interleaved chunks: chunk c of rank r is substage
    # c*S + r (the virtual-stage numbering docs/pipeline.md draws)
    wi = ws16.reshape(2, stages, DIM, DIM).transpose(1, 0, 2, 3)

    ref = np.asarray(reference(x0, ws16))

    # --- the naive ladder (the MPX135 positive)
    ladder = make_ladder(comm)
    x = jnp.zeros((stages, BATCH, DIM), jnp.float32).at[0].set(x0)
    out = ladder(x, w2s)
    np.testing.assert_array_equal(np.asarray(out[-1]), ref)

    # --- the compiled schedules: global microbatch view, stage 0 real
    mbs = jnp.zeros((stages, MICROBATCHES, mb, DIM), jnp.float32).at[0].set(
        split_microbatches(x0, MICROBATCHES))
    for label, prog, params in (
        ("gpipe", mpx.pipeline(stage_pair, MICROBATCHES,
                               schedule="gpipe", comm=comm), w2s),
        ("1f1b", mpx.pipeline(stage_pair, MICROBATCHES,
                              schedule="1f1b", comm=comm), w2s),
        ("interleaved", mpx.pipeline(substage, MICROBATCHES,
                                     schedule="interleaved", virtual=2,
                                     comm=comm), wi),
        ("auto", mpx.pipeline(stage_pair, MICROBATCHES, comm=comm), w2s),
    ):
        got = prog(mbs, params)
        np.testing.assert_array_equal(
            np.asarray(got[-1]).reshape(BATCH, DIM), ref,
            err_msg=f"schedule {label!r} diverged from the reference")
        plan = prog.plan(stages, MICROBATCHES, mb * DIM * 4)
        print(f"{label:<12} -> {plan.schedule}: warmup {plan.warmup} / "
              f"steady {plan.steady} / cooldown {plan.cooldown} tick(s), "
              f"activation stash <= {plan.max_stash}")

    print(f"pipeline over {stages} stage(s): the ladder and every "
          f"compiled schedule match the sequential reference bit for bit")

    # the cost model's verdict on the naive ladder: a serialized p2p
    # chain on the critical path (MPX135), its text citing the modeled
    # bubble fraction and the mpx.pipeline fix
    report = mpx.analyze(ladder, x, w2s, ranks="all", cost=True)
    chain = [f for f in report.findings if f.code == "MPX135"]
    if chain:
        print(f"cost model: {chain[0].message}")
        print(f"cost model: {chain[0].suggestion}")
    if report.cost is not None:
        print(f"predicted step time (naive ladder): "
              f"{report.cost.total_us:.1f} us")


if __name__ == "__main__":
    main()
