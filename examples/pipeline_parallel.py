"""Pipeline parallelism: a minimal GPipe-style microbatch ladder.

The first pipeline-shaped program in the examples suite (ROADMAP item
5): eight pipeline stages, one per rank, each applying its own weight
matrix.  Two variants of the SAME forward pass:

- ``pipeline_fwd`` — the **naive ladder**: the whole batch enters stage
  0 and crawls stage to stage over matched ``send``/``recv`` pairs.
  Every hop waits for the previous stage's full compute + transfer, so
  the S-1 hops serialize end to end.  This is the seeded positive for
  the cost model's **MPX135** advisory (serialized point-to-point chain
  on the critical path)::

      python -m mpi4jax_tpu.analysis --ranks 8 --cost \
          examples/pipeline_parallel.py

  reports MPX135 (advisory — exit code stays 0) with the chain's
  predicted share of the step time;

- ``pipeline_fwd_microbatched`` — the **GPipe fix**: the batch splits
  into M microbatches injected one per wavefront tick, every stage
  boundary shipping simultaneously (one ``sendrecv`` shift per tick),
  so stage i+1's transfer of microbatch m overlaps stage i's compute of
  microbatch m+1.  Same math — the driver asserts both variants match
  the sequential reference bit for bit — but the chain is pipelined.

Without ``--cost`` both variants verify clean: the ladder is *correct*
(every send matched, no deadlock, tokens threaded); only the cost
model can say it is *slow*.  See docs/analysis.md "Cost model".

Run: python examples/pipeline_parallel.py   (8 devices, e.g.
     XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

import os
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import mpi4jax_tpu as mpx  # noqa: E402

MICROBATCHES = 4


def stage_fn(h, w):
    """One pipeline stage: a linear layer + nonlinearity."""
    return jnp.tanh(h @ w)


def make_pipeline(comm):
    """Build both pipeline variants over ``comm`` (one stage per rank).

    Inputs are global arrays (leading axis = ranks): ``x[0]`` /
    ``mbs[0]`` hold stage 0's real minibatch, ``ws[s]`` is stage s's
    weight matrix.  The result lives on the LAST stage's row of the
    global output.
    """
    stages = comm.Get_size()

    @mpx.spmd(comm=comm)
    def pipeline_fwd(x, w):
        # the naive ladder: compute, ship the whole activation to the
        # next stage, wait, repeat — S-1 serialized hops (MPX135)
        rank = comm.Get_rank()
        h = stage_fn(x, w)  # stage 0's lane holds the real value
        tok = None
        for s in range(1, stages):
            tok = mpx.send(h, dest={s - 1: s}, tag=s, token=tok)
            got, tok = mpx.recv(h, source={s - 1: s}, tag=s, token=tok)
            h = jnp.where(rank == s, stage_fn(got, w), h)
        return h

    @mpx.spmd(comm=comm)
    def pipeline_fwd_microbatched(mbs, w):
        # the GPipe wavefront: one shift per tick moves EVERY stage
        # boundary at once; microbatch m's hop overlaps microbatch
        # m+1's compute one stage upstream
        rank = comm.Get_rank()
        m = mbs.shape[0]
        h = jnp.zeros_like(mbs[0])
        outs = []
        tok = None
        for t in range(stages + m - 1):
            got, tok = mpx.sendrecv(
                h, h, dest=mpx.shift(1, wrap=False), token=tok)
            feed = mbs[t] if t < m else jnp.zeros_like(mbs[0])
            src = jnp.where(rank == 0, feed, got)
            h = stage_fn(src, w)
            outs.append(h)
        # microbatch m leaves the last stage at tick m + stages - 1
        return jnp.stack([outs[i + stages - 1] for i in range(m)])

    return pipeline_fwd, pipeline_fwd_microbatched


def reference(x0, ws):
    """Sequential single-device reference: the full stage composition."""
    h = x0
    for s in range(ws.shape[0]):
        h = stage_fn(h, ws[s])
    return h


def main():
    comm = mpx.get_default_comm()
    stages = comm.Get_size()
    batch, dim = 8, 16
    assert batch % MICROBATCHES == 0
    rng = np.random.default_rng(0)

    x = jnp.zeros((stages, batch, dim), jnp.float32).at[0].set(
        jnp.asarray(rng.normal(size=(batch, dim)), jnp.float32))
    ws = jnp.asarray(rng.normal(size=(stages, dim, dim)) * 0.5,
                     jnp.float32)
    pipeline_fwd, pipeline_fwd_microbatched = make_pipeline(comm)

    ref = reference(x[0], ws)

    out = pipeline_fwd(x, ws)
    np.testing.assert_allclose(out[-1], ref, rtol=1e-5, atol=1e-5)

    mb = batch // MICROBATCHES
    mbs = jnp.zeros((stages, MICROBATCHES, mb, dim), jnp.float32).at[0].set(
        x[0].reshape(MICROBATCHES, mb, dim))
    out_mb = pipeline_fwd_microbatched(mbs, ws)
    np.testing.assert_allclose(out_mb[-1].reshape(batch, dim), ref,
                               rtol=1e-5, atol=1e-5)

    print(f"pipeline over {stages} stage(s): naive ladder and "
          f"{MICROBATCHES}-microbatch wavefront both match the "
          "sequential reference")

    # the cost model's verdict on the naive ladder: a serialized p2p
    # chain on the critical path (MPX135) — the microbatched variant is
    # the recommended fix
    report = mpx.analyze(pipeline_fwd, x, ws, ranks="all", cost=True)
    chain = [f for f in report.findings if f.code == "MPX135"]
    if chain:
        print(f"cost model: {chain[0].message}")
    if report.cost is not None:
        print(f"predicted step time (naive ladder): "
              f"{report.cost.total_us:.1f} us")


if __name__ == "__main__":
    main()
