"""Collective fusion + async overlap — the throughput layer end to end
(docs/overlap.md).

Three stages on the same mesh, printing what each mechanism did:

1. **fusion** (``MPI4JAX_TPU_FUSION=auto``): sixteen small per-leaf
   allreduces issued batch-first coalesce into one flat-buffer
   collective per dtype bucket — the telemetry meters show the buckets
   formed and the member ops packed;
2. **explicit start/wait**: an allreduce split into chunked
   double-buffered ring phases with independent compute in the gap;
3. **mpx.overlap() region**: the same split, implicit — the wait is
   emitted at the result's first use.

Verified clean by the trace-time verifier in CI
(``python -m mpi4jax_tpu.analysis examples/fusion_overlap_demo.py``):
with fusion ON there are no MPX111 advisories to fire, and every start
is paired (MPX112).

Run: python examples/fusion_overlap_demo.py
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def main():
    devices = jax.devices()
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()

    # --- 1. fusion: many small collectives -> one flat-buffer collective
    mpx.set_fusion_mode("auto")
    mpx.set_telemetry_mode("counters")
    try:
        leaves = [jnp.full((n, 64 * (i % 3 + 1)), float(i + 1), jnp.float32)
                  for i in range(16)]

        @mpx.spmd(comm=comm)
        def fused_sum(xs):
            # issue the whole batch, then consume: the first use flushes
            # ONE fused allreduce (docs/overlap.md)
            red = [mpx.allreduce(x, op=mpx.SUM)[0] for x in xs]
            return [mpx.varying(r * (1.0 / n)) for r in red]

        out = fused_sum(tuple(leaves))
        np.testing.assert_allclose(np.asarray(out[2])[0, 0], 3.0, rtol=1e-6)
        meters = mpx.telemetry.snapshot()["meters"]
        buckets = sum(v for k, v in meters.items()
                      if k.startswith("fusion.") and k.endswith(".buckets"))
        members = sum(v for k, v in meters.items()
                      if k.startswith("fusion.") and k.endswith(".members"))
        print(f"fusion: {members} member allreduces -> {buckets} fused "
              f"flat-buffer collective(s)")
    finally:
        mpx.set_fusion_mode(None)
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()

    # --- 2. explicit start/wait: compute overlaps the wire phases
    @mpx.spmd(comm=comm)
    def split_step(g, m):
        h, tok = mpx.allreduce_start(g, op=mpx.SUM)
        m = jnp.tanh(m @ m)          # independent: overlaps both phases
        s, tok = mpx.allreduce_wait(h, token=tok)
        return mpx.varying(s * (1.0 / n)), m

    g = jnp.ones((n, 4096), jnp.float32)
    m = jnp.full((n, 32, 32), 0.01, jnp.float32)
    avg, m2 = split_step(g, m)
    np.testing.assert_allclose(np.asarray(avg)[0, :3], 1.0, rtol=1e-6)
    print(f"start/wait: chunked ring allreduce of {g.shape[-1]} floats "
          f"with a {m.shape[-1]}x{m.shape[-1]} matmul chain in the gap")

    # --- 3. the implicit form: mpx.overlap()
    @mpx.spmd(comm=comm)
    def overlap_step(g, m):
        with mpx.overlap():
            s, _ = mpx.allreduce(g, op=mpx.SUM)   # start emitted here
            m = jnp.tanh(m @ m)                   # overlaps
            out = s * (1.0 / n)                   # first use -> wait
        return mpx.varying(out), m

    avg2, _ = overlap_step(g, m)
    np.testing.assert_allclose(np.asarray(avg2), np.asarray(avg), rtol=1e-6)
    print(f"overlap(): same result, wait emitted at first use "
          f"({n} device(s))")


if __name__ == "__main__":
    main()
