"""Data-parallel SGD with gradient allreduce — the reference's core ML
use case (SURVEY.md §2.6(2): the differentiable allreduce exists for
DP-SGD / NetKet-style VMC gradient sums).

Each rank holds a shard of the batch; the loss gradient is averaged
across ranks with one differentiable ``allreduce`` per step, inside the
same jitted SPMD program as the backward pass — so XLA overlaps the
gradient AllReduce with the remaining backward compute (the standard
TPU DP pattern, here expressed through the MPI-style API).

The gradient exchange goes through ``mpx.compress.ef_allreduce`` — the
error-feedback form of the tree-mapped allreduce (docs/compression.md).
With the knob off (the default) it IS the plain exact allreduce and the
residual stays zero; under ``MPI4JAX_TPU_COMPRESS=bf16`` (or ``fp8``)
the inter-host leg ships compressed and the residual carries each
step's quantization error into the next — this file doubles as the
convergence harness's measured lane (CI's ``compress`` job runs it
per codec and asserts loss-curve parity against the exact run;
the committed record is BENCH_compress.json).

Run: python examples/data_parallel_training.py [--steps N] [--seed S]
         [--out losses.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def init_mlp(key, sizes):
    params = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        key, wk = jax.random.split(key)
        params.append({
            "w": jax.random.normal(wk, (fan_in, fan_out)) * (2.0 / fan_in) ** 0.5,
            "b": jnp.zeros((fan_out,)),
        })
    return params


def mlp_apply(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    last = params[-1]
    return x @ last["w"] + last["b"]


def local_loss(params, x, y):
    pred = mlp_apply(params, x)
    return jnp.mean((pred - y) ** 2)


def make_train_step(comm: mpx.Comm, lr: float):
    """One DP-SGD step: local grad -> EF allreduce(SUM)/size -> update.

    Weights enter replicated (identical on every rank, like the
    reference's per-process copies); the averaged gradient keeps them in
    lock-step without any parameter broadcast.  The residual is part of
    the train state: zero (and dead code) with compression off, the
    carried quantization error under bf16/fp8.
    """
    size = comm.Get_size()

    @mpx.spmd(comm=comm)
    def train_step(params, residual, x, y):
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        # the fusion-friendly idiom (docs/overlap.md) still holds: the
        # EF allreduce issues every per-leaf collective before any is
        # consumed — under MPI4JAX_TPU_FUSION=auto the batch coalesces
        # into ONE flat-buffer collective; with fusion off the calls
        # run one by one, same math either way
        red, residual, token = mpx.compress.ef_allreduce(
            grads, residual, op=mpx.SUM, comm=comm)
        loss = mpx.allreduce(loss, op=mpx.SUM, comm=comm,
                             token=token)[0] / size
        new_params = jax.tree.map(lambda p, g: p - lr * (g / size),
                                  params, red)
        return mpx.varying((new_params, residual, loss))

    return train_step


def replicate(tree, size):
    """Stack ``size`` identical copies along a leading rank axis."""
    return jax.tree.map(lambda v: jnp.tile(v[None], (size,) + (1,) * v.ndim), tree)


def main(steps: int = 200, seed: int = 0, out: str = ""):
    devices = jax.devices()
    size = len(devices)
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)

    # synthetic regression task, sharded over ranks
    key = jax.random.PRNGKey(seed)
    key, kx, kn = jax.random.split(key, 3)
    per_rank = 64
    x = jax.random.normal(kx, (size, per_rank, 16))
    w_true = jax.random.normal(kn, (16, 1))
    y = jnp.tanh(x @ w_true)

    params = replicate(init_mlp(key, (16, 64, 1)), size)
    # the EF residual rides in the train state, one row per rank;
    # exactly zero for the whole run when compression is off
    residual = mpx.compress.ef_zeros_like(params)
    train_step = make_train_step(comm, lr=1e-2)
    losses = []

    # coalesce the per-leaf gradient allreduces into one flat-buffer
    # collective per step (Horovod-style tensor fusion, docs/overlap.md);
    # reset below so this demo leaves no global state behind
    mpx.set_fusion_mode("auto")
    try:
        t0 = time.perf_counter()
        for step in range(steps):
            params, residual, loss = train_step(params, residual, x, y)
            losses.append(float(np.asarray(loss)[0]))
            if step % 50 == 0 or step == steps - 1:
                print(f"step {step:4d}  loss {losses[-1]:.5f}")
        wall = time.perf_counter() - t0
    finally:
        mpx.set_fusion_mode(None)

    # weights must be identical on every rank (replicated DP invariant)
    for leaf in jax.tree.leaves(params):
        leaf = np.asarray(leaf)
        np.testing.assert_allclose(leaf, np.broadcast_to(leaf[0], leaf.shape),
                                   rtol=1e-6)
    mode = mpx.compress.compress_mode()
    if out:
        with open(out, "w") as f:
            json.dump({"compress": mode, "steps": steps, "seed": seed,
                       "world": size, "losses": losses}, f, indent=2)
    print(f"{steps} steps on {size} device(s) in {wall:.2f}s "
          f"(compress={mode}) — weights in lock-step on all ranks")
    return params


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="",
                    help="write the per-step loss curve as JSON here "
                         "(the compress lane's parity input)")
    a = ap.parse_args()
    main(steps=a.steps, seed=a.seed, out=a.out)
