"""Continuous-batching serving: the deployment, benchmark, and drain drill.

The serving runtime end to end (docs/serving.md): a tensor-parallel
transformer decode loop served by the iteration-level batching scheduler
— per-(bucket, phase) programs pinned through ``mpx.compile``, decode as
a device-resident megastep, admission/eviction at megastep boundaries,
KV slots scatter-managed so churn never retraces.  Three modes:

- **benchmark** (default): serve one synthetic Poisson trace with the
  CONTINUOUS scheduler and again with the STATIC batch baseline, and
  write both numbers — tokens/s/chip at the p99 latency bound — to
  ``--out`` (the ``BENCH_serving.json`` schema)::

      python examples/serving/serve.py --scheduler both --json \\
          --out BENCH_serving.json

- **simulate** (``--simulate``): the same trace through the same
  scheduler on the cost-model replay (serving/sim.py) — no devices
  touched; the capture path for containers without an accelerator;

- **drain drill** (``--launch N``): N worker processes serve one trace
  in lockstep (virtual clock); at ``--drain-boundary`` the drained rank
  posts its preemption notice (the same ``request_drain`` path a
  SIGTERM or the ``preempt`` fault verb feeds), the world executes the
  planned shrink at the next megastep boundary, survivors re-shard the
  committed parameters, RE-ADMIT every in-flight sequence from its
  committed token history, and finish the trace with ZERO failed
  requests — exactly one ``drain`` incident per journal
  (the PR 9 drill routed through the serving loop)::

      MPI4JAX_TPU_TELEMETRY=events MPI4JAX_TPU_TELEMETRY_DIR=/tmp/srv \\
          python examples/serving/serve.py --launch 3 --drain-rank 2
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

DONE_TAG = "SERVING_DONE"
DRAINED_TAG = "SERVING_DRAINED"

# model presets: "tiny" traces/compiles in seconds on the CI CPU mesh —
# and matches the ServingConfig dataclass defaults EXACTLY, so programs
# warmed from `aot warm --emit-manifest` (which reads those defaults)
# hit the same cache keys a tiny serve run asks for; "bench" is the
# serving-number workload (realistic weight traffic)
PRESETS = {
    "tiny": dict(heads=24, head_dim=4, ffn=384, max_len=48, max_prompt=16),
    "bench": dict(heads=24, head_dim=64, ffn=6144, max_len=160,
                  max_prompt=16),
}


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", choices=sorted(PRESETS), default="tiny")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=50.0,
                   help="Poisson arrival rate (requests/s)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--long-frac", type=float, default=0.25,
                   help="fraction of requests drawing the heavy-tail "
                        "generation budget")
    p.add_argument("--unroll", type=int, default=0,
                   help="decode megastep trip count (0 = the "
                        "MPI4JAX_TPU_SERVING_UNROLL default)")
    p.add_argument("--max-batch", type=int, default=0,
                   help="0 = the MPI4JAX_TPU_SERVING_MAX_BATCH default")
    p.add_argument("--slo-ms", type=float, default=0.0,
                   help="p99 latency bound (0 = the "
                        "MPI4JAX_TPU_SERVING_SLO_P99_MS default)")
    p.add_argument("--scheduler", choices=("continuous", "static", "both"),
                   default="both")
    p.add_argument("--simulate", action="store_true",
                   help="cost-model replay instead of real devices")
    p.add_argument("--virtual-clock", action="store_true",
                   help="advance arrivals one tick per megastep boundary "
                        "(deterministic across ranks; implied by --launch)")
    p.add_argument("--json", action="store_true",
                   help="print ONLY the JSON payload")
    p.add_argument("--out", default="",
                   help="write the BENCH_serving.json payload here")
    # drain drill plumbing
    p.add_argument("--launch", type=int, default=0, metavar="N",
                   help="launch an N-process drill world")
    p.add_argument("--drain-rank", type=int, default=-1,
                   help="drill: rank that receives the preemption notice "
                        "(-1 = last)")
    p.add_argument("--drain-boundary", type=int, default=4,
                   help="drill: megastep boundary at which the notice "
                        "lands")
    p.add_argument("--process-id", type=int, default=-1,
                   help=argparse.SUPPRESS)
    p.add_argument("--num-processes", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--port-base", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--drill-timeout", type=float, default=540.0,
                   help=argparse.SUPPRESS)
    return p.parse_args(argv)


def _config(args, mpx_serving):
    overrides = dict(PRESETS[args.model], seed=args.seed)
    if args.unroll:
        overrides["unroll"] = args.unroll
    if args.max_batch:
        overrides["max_batch"] = args.max_batch
    if args.slo_ms:
        overrides["slo_p99_ms"] = args.slo_ms
    if args.virtual_clock or args.launch or args.process_id >= 0:
        overrides["clock"] = "virtual"
    return mpx_serving.ServingConfig.from_env(**overrides)


def _trace(args, cfg, mpx_serving):
    # budgets scale with the model's KV row so every preset saturates
    # its lanes: short answers for most requests, a heavy tail of long
    # ones — the regime where static batching idles lanes
    short_hi = max(4, (cfg.max_len - cfg.max_prompt) // 8)
    long_hi = cfg.max_len - cfg.max_prompt - cfg.unroll - 1
    trace = mpx_serving.poisson_trace(
        args.requests, args.rate, seed=args.seed,
        prompt_len=(2, min(6, cfg.max_prompt)),
        max_new=(4, short_hi),
        long_frac=args.long_frac,
        long_new=(max(short_hi + 1, 3 * long_hi // 4), long_hi),
        vocab=cfg.vocab,
    )
    meta = {
        "requests": args.requests, "rate_rps": args.rate,
        "seed": args.seed, "long_frac": args.long_frac,
        "span_s": round(trace[-1].arrival_s, 4),
        "tokens_budgeted": sum(r.max_new_tokens for r in trace),
    }
    return trace, meta


def _emit(args, payload):
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(payload) if args.json
          else json.dumps(payload, indent=2))


def run_simulate(args):
    from mpi4jax_tpu import serving
    from mpi4jax_tpu.serving import sim

    cfg = _config(args, serving)
    trace, meta = _trace(args, cfg, serving)
    import jax

    k = jax.device_count()
    cfg.validate_world(k)
    payload, _, _ = sim.replay_bench(cfg, trace, k=k, trace_meta=meta)
    _emit(args, payload)


def run_benchmark(args):
    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import serving

    cfg = _config(args, serving)
    trace, meta = _trace(args, cfg, serving)
    comm = mpx.get_default_comm()
    k = comm.world_size()

    results = {}
    schedulers = (("continuous", "static") if args.scheduler == "both"
                  else (args.scheduler,))
    for sched in schedulers:
        engine = serving.ServingEngine(cfg, comm)
        results[sched] = engine.run(trace, scheduler=sched)
        if not args.json:
            r = results[sched]
            print(f"{sched:>10}: {r['tokens_per_s_per_chip']} tok/s/chip, "
                  f"p99 {r['p99_ms']} ms (slo {r['slo_p99_ms']} ms, "
                  f"met={r['slo_met']}), {r['completed']} completed / "
                  f"{r['failed']} failed over {r['boundaries']} "
                  "boundaries", file=sys.stderr)

    cont = results.get("continuous") or results[args.scheduler]
    payload = serving.bench_payload(
        workload=cfg.workload_meta(k), trace_meta=meta, chips=k,
        continuous=cont, static=results.get("static"),
        environment=(f"measured: {k}-device "
                     "mesh (examples/serving/serve.py)"),
    )
    from mpi4jax_tpu.aot import stats as aot_stats

    payload["compile_cache"] = aot_stats()
    _emit(args, payload)


# ---------------------------------------------------------------------------
# the drain drill: --launch parent + worker halves
# ---------------------------------------------------------------------------


def run_worker(args):
    import jax

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu import serving
    from mpi4jax_tpu.parallel import megastep

    mpx.init_distributed(
        coordinator_address=f"localhost:{args.port_base}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.device_count() == args.num_processes

    cfg = _config(args, serving)
    trace, _ = _trace(args, cfg, serving)
    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    store = mpx.ShardStore(comm, bootstrap={
        "host": "localhost",
        "port_base": args.port_base,
        "process_id": args.process_id,
        "num_processes": args.num_processes,
        "agree_port_base": args.port_base + 100,
    })
    engine = serving.ServingEngine(cfg, comm, store=store)

    drain_rank = (args.drain_rank if args.drain_rank >= 0
                  else args.num_processes - 1)

    posted = []

    def preemption_notice(step, **info):
        # the preemption notice lands ONCE, at the first boundary past
        # --drain-boundary with sequences IN FLIGHT (deterministic and
        # identical on every rank: the scheduler state is replicated),
        # so the drill always exercises the re-admission path.  Same
        # request_drain path a SIGTERM (BoundaryControl installs the
        # handler) or the `preempt` fault verb feeds.
        eng = info.get("engine")
        if (not posted and step >= args.drain_boundary
                and args.process_id == drain_rank
                and eng is not None and eng._sched.running):
            posted.append(step)
            mpx.request_drain()

    unregister = megastep.register_boundary_hook("drill-preempt",
                                                 preemption_notice)
    try:
        result = engine.run(trace, scheduler="continuous")
    finally:
        unregister()

    tag = DRAINED_TAG if engine.drained else DONE_TAG
    print(f"{tag} world={result['world']} completed={result['completed']} "
          f"failed={result['failed']} "
          f"readmissions={result['preempt_readmissions']}", flush=True)
    assert result["failed"] == 0, result
    if not engine.drained:
        assert result["completed"] == len(trace), result
        assert result["world"] == args.num_processes - 1, result
        assert result["preempt_readmissions"] > 0, (
            "the drain boundary should have re-admitted in-flight "
            f"sequences: {result}")


def run_launcher(args):
    """Spawn the drill world; success = every worker exits 0, exactly
    one prints the drained tag, and every survivor reports the full
    trace completed with zero failures."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port_base = s.getsockname()[1]

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    n = args.launch

    def spawn(i):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--process-id", str(i), "--num-processes", str(n),
               "--port-base", str(port_base),
               "--model", args.model,
               "--requests", str(args.requests),
               "--rate", str(args.rate), "--seed", str(args.seed),
               "--long-frac", str(args.long_frac),
               "--drain-rank", str(args.drain_rank),
               "--drain-boundary", str(args.drain_boundary)]
        if args.unroll:
            cmd += ["--unroll", str(args.unroll)]
        if args.max_batch:
            cmd += ["--max-batch", str(args.max_batch)]
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    workers = [spawn(i) for i in range(n)]
    deadline = time.monotonic() + args.drill_timeout
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in workers):
            break
        time.sleep(0.5)
    else:
        for p in workers:
            p.kill()
        print("drill timeout", file=sys.stderr)
        return 1

    drained = done = failures = 0
    for i, p in enumerate(workers):
        out = p.stdout.read()
        sys.stderr.write(f"--- worker {i} (rc={p.returncode}) ---\n{out}\n")
        if p.returncode != 0:
            failures += 1
        if DRAINED_TAG in out:
            drained += 1
        if DONE_TAG in out:
            done += 1
    ok = failures == 0 and drained == 1 and done == n - 1
    print(f"drill: {done} survivor(s) done, {drained} drained, "
          f"{failures} failure(s) -> {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


def main():
    args = _parse_args()
    if args.launch:
        sys.exit(run_launcher(args))
    if args.process_id >= 0:
        run_worker(args)
    elif args.simulate:
        run_simulate(args)
    else:
        run_benchmark(args)


if __name__ == "__main__":
    main()
