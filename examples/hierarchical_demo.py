"""Hierarchical topology-aware collectives — the two-level ICI/DCN layer
end to end (docs/topology.md).

Run plain (single host: every comm is one-host, the hierarchy stays out
of the way and the flat algorithms run) or under a faked multi-host
topology, the way the CI topology lane does on the 8-device CPU mesh:

    MPI4JAX_TPU_TOPOLOGY=2x4 python examples/hierarchical_demo.py

Three stages, printing what the topology layer did:

1. **plan** — what host partition was derived for the world comm and
   whether the two-level decomposition is expressible;
2. **equivalence** — the SAME ``PROD`` allreduce, broadcast, and
   reduce_scatter forced through the flat ring and the two-level
   lowering must agree (the trace-level proof lives in
   tests/test_hierarchy.py's lockstep simulator, and the program-cache
   keys retrace per setting);
3. **telemetry** — counters-tier per-link-class byte split: the
   hierarchical allreduce lands its modeled wire bytes on the
   ``intra_host`` (ICI) and ``inter_host`` (DCN) classes
   (docs/observability.md).

Verified clean by the trace-time verifier in CI
(``python -m mpi4jax_tpu.analysis examples/hierarchical_demo.py``), with
and without the topology faked: payloads stay below the ring crossover,
so the forced-flat sections never trip the MPX113 advisory.
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.ops._hierarchy import hier_plan  # noqa: E402


class _forced_algo:
    """Temporarily force MPI4JAX_TPU_COLLECTIVE_ALGO (folded into the
    program-cache keys, so each setting traces its own program)."""

    def __init__(self, algo):
        self.algo = algo

    def __enter__(self):
        self.saved = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
        os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = self.algo

    def __exit__(self, *exc):
        if self.saved is None:
            os.environ.pop("MPI4JAX_TPU_COLLECTIVE_ALGO", None)
        else:
            os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = self.saved
        return False


def main():
    devices = jax.devices()
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()

    # --- 1. the plan: what the topology layer sees
    spec = os.environ.get("MPI4JAX_TPU_TOPOLOGY", "")
    plan = hier_plan(comm)
    if plan is None:
        print(f"topology: {spec or 'derived from mesh'} -> no multi-host "
              f"hierarchy for this {n}-device comm (flat algorithms "
              "everywhere — the correct no-op)")
    else:
        print(f"topology: {spec or 'derived from mesh'} -> "
              f"{plan.h} hosts x {plan.r} ranks/host; two-level "
              "lowerings available")

    # --- 2. equivalence: flat ring vs the forced two-level lowering
    x = jnp.stack([
        jnp.full((4096,), 1.0 + 0.001 * r, jnp.float32) for r in range(n)
    ])
    blocks = jnp.stack([
        jnp.arange(n * 8, dtype=jnp.float32).reshape(n, 8) + r
        for r in range(n)
    ])
    results = {}
    for algo in ("ring", "hier"):
        with _forced_algo(algo):

            @mpx.spmd(comm=comm)
            def prog(v, b):
                s, tok = mpx.allreduce(v, op=mpx.PROD)
                c, tok = mpx.bcast(b[0], root=1, token=tok)
                d, _ = mpx.reduce_scatter(b, op=mpx.SUM, token=tok)
                return mpx.varying(s), mpx.varying(c), mpx.varying(d)

            results[algo] = [np.asarray(o) for o in prog(x, blocks)]
    for flat_out, hier_out in zip(results["ring"], results["hier"]):
        np.testing.assert_allclose(flat_out, hier_out, rtol=1e-6)
    print("equivalence: PROD allreduce + bcast + reduce_scatter agree "
          "between the flat ring and the two-level lowering")

    # --- 3. telemetry: the per-link-class byte split
    mpx.set_telemetry_mode("counters")
    try:
        with _forced_algo("hier" if plan is not None else "ring"):

            @mpx.spmd(comm=comm)
            def counted(v):
                s, _ = mpx.allreduce(v, op=mpx.PROD)
                return mpx.varying(s)

            counted(x)
        rows = mpx.telemetry.snapshot()["ops"].values()
        for row in rows:
            print(f"telemetry: {row['op']} algo={row['algo']} "
                  f"intra_host={row['intra_bytes']} B "
                  f"inter_host={row['inter_bytes']} B "
                  f"(payload {row['bytes']} B)")
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


if __name__ == "__main__":
    main()
