"""Elastic data-parallel training: survive rank loss and keep going.

The acceptance drill for the elastic-recovery layer
(docs/resilience.md "Elastic recovery"): a DP-SGD loop wrapped in
``mpx.elastic.run`` with a ``ShardStore`` in-memory checkpoint.  When a
rank dies (or hangs) mid-run, the survivors agree on the failed set,
revoke the communication epoch, shrink the mesh/comm to "all minus
failed", restore the last committed state from the surviving shard
replicas, and finish the step budget on ``k - f`` ranks.

Two modes:

- **single process** (default): all local devices form the world; a
  simulated :class:`RankFailure` fires at ``--fail-step`` and the mesh
  shrinks in place —

      python examples/elastic_training.py

- **multi-process drill** (``--launch N``): N worker processes (one CPU
  device each) over ``jax.distributed``; kill one with the fault
  injector and the survivors re-bootstrap a smaller world —

      MPI4JAX_TPU_FAULT_SPEC='die:rank=3:op=allreduce:after=5' \\
        python examples/elastic_training.py --launch 4 --steps 12

  The parent exits 0 iff a surviving majority completed the full step
  budget.  Swap ``die`` for ``hang`` to drill the watchdog-expiry
  detection path (the loop claims the expiry handler while it runs).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

DONE_TAG = "ELASTIC_DONE"


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=12,
                   help="total training steps to complete (the budget)")
    p.add_argument("--commit-every", type=int, default=1,
                   help="commit the state to the ShardStore every N steps")
    p.add_argument("--fail-step", type=int, default=5,
                   help="single-process mode: step at which the simulated "
                        "failure fires (<0 disables)")
    p.add_argument("--fail-rank", type=int, default=-1,
                   help="single-process mode: rank to fail (-1 = last)")
    p.add_argument("--out", default="",
                   help="write the per-step loss trace as JSON here")
    # multi-process drill plumbing
    p.add_argument("--launch", type=int, default=0, metavar="N",
                   help="launch an N-process world and run the drill")
    p.add_argument("--process-id", type=int, default=-1,
                   help=argparse.SUPPRESS)  # worker-internal
    p.add_argument("--num-processes", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--port-base", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--watchdog", type=float, default=30.0,
                   help="multi-process drill: watchdog timeout in seconds "
                        "(the hang-drill detection bound)")
    p.add_argument("--drill-timeout", type=float, default=540.0,
                   help="--launch parent: seconds before the drill fails")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# the model + elastic step (shared by both modes)
# ---------------------------------------------------------------------------


def _init_params(dim=16, hidden=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": rng.normal(0, dim ** -0.5, (dim, hidden)).astype(np.float32),
        "b1": np.zeros((hidden,), np.float32),
        "w2": rng.normal(0, hidden ** -0.5, (hidden, 1)).astype(np.float32),
        "b2": np.zeros((1,), np.float32),
    }


def _data_for(k, per_rank=32, dim=16, seed=1):
    """Synthetic regression data with a leading rank axis, derived from
    the CURRENT world size — after a shrink the survivors re-derive it
    at k-f (every process computes the same arrays: same seed)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(k, per_rank, dim)).astype(np.float32)
    w = rng.normal(size=(dim, 1)).astype(np.float32)
    y = np.tanh(x @ w).astype(np.float32)
    return x, y


def _make_elastic_step(mpx, lr=0.05):
    """``step_fn(state, step, comm)`` for ``mpx.elastic.run``: builds (and
    caches) one SPMD program per comm — after a shrink the new comm gets a
    fresh program traced at the new size (the epoch in the cache key
    guarantees the old one is unreachable anyway)."""
    import jax
    import jax.numpy as jnp

    programs = {}

    def train_step_for(comm):
        key = (comm.uid, comm.epoch)
        if key not in programs:
            size = comm.Get_size()

            @mpx.spmd(comm=comm)
            def train_step(params, x, y):
                def loss_fn(p, x, y):
                    h = jax.nn.relu(x @ p["w1"] + p["b1"])
                    pred = h @ p["w2"] + p["b2"]
                    return jnp.mean((pred - y) ** 2)

                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                red = jax.tree.map(
                    lambda g: mpx.allreduce(g, op=mpx.SUM, comm=comm)[0],
                    grads)
                loss = mpx.allreduce(loss, op=mpx.SUM, comm=comm)[0] / size
                new = jax.tree.map(lambda p, g: p - lr * (g / size),
                                   params, red)
                return mpx.varying((new, loss))

            programs[key] = train_step
        return programs[key]

    def replicate(tree, k):
        return jax.tree.map(
            lambda v: jnp.tile(jnp.asarray(v)[None], (k,) + (1,) * v.ndim),
            tree)

    losses = []

    def step_fn(state, step, comm):
        k = comm.Get_size()
        x, y = _data_for(k)
        params_g = replicate(state["params"], k)
        params_g, loss = train_step_for(comm)(params_g, x, y)
        loss = float(np.asarray(loss)[0])
        losses.append({"step": step, "world": k, "loss": loss,
                       "epoch": comm.epoch})
        print(f"step {step:3d}  world {k}  epoch {comm.epoch}  "
              f"loss {loss:.6f}", flush=True)
        # state stays single-copy (replicated invariant: every rank's row
        # is identical, row 0 is the canonical copy the ShardStore shards)
        return {"params": jax.tree.map(lambda v: np.asarray(v[0]), params_g)}

    return step_fn, losses


# ---------------------------------------------------------------------------
# single-process mode: simulated failure, in-place mesh shrink
# ---------------------------------------------------------------------------


def run_single(args):
    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    k = comm.Get_size()
    fail_rank = args.fail_rank if args.fail_rank >= 0 else k - 1
    fail_at = args.fail_step if 0 <= args.fail_step < args.steps else None
    if fail_at is not None and k < 2:
        print("single device: nothing to shrink, running clean")
        fail_at = None

    store = mpx.ShardStore(comm)
    base_step, losses = _make_elastic_step(mpx)

    def step_fn(state, step, comm):
        state = base_step(state, step, comm)
        if fail_at is not None and step == fail_at and comm.epoch == 0:
            # simulate rank loss AFTER the step's work (a real death
            # surfaces as an error/expiry inside the next collective; the
            # recovery path from here on is identical)
            raise mpx.RankFailure({fail_rank},
                                  f"simulated loss of rank {fail_rank}")
        return state

    state = {"params": _init_params()}
    state = mpx.elastic.run(step_fn, state, store, steps=args.steps,
                            commit_every=args.commit_every)

    final_world = store.comm.Get_size()
    expect_world = k - 1 if fail_at is not None else k
    assert final_world == expect_world, (final_world, expect_world)
    assert len([r for r in losses if r["step"] == args.steps - 1]) == 1
    if fail_at is not None:
        from mpi4jax_tpu.resilience import elastic as el

        assert el.current_epoch() == 1, el.current_epoch()
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"losses": losses, "final_world": final_world}, f,
                      indent=2)
    print(f"{DONE_TAG} steps={args.steps} world={final_world}", flush=True)
    return state


# ---------------------------------------------------------------------------
# multi-process drill: --launch parent + worker halves
# ---------------------------------------------------------------------------


def run_worker(args):
    import jax

    import mpi4jax_tpu as mpx

    mpx.init_distributed(
        coordinator_address=f"localhost:{args.port_base}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.device_count() == args.num_processes

    if args.watchdog > 0:
        mpx.set_watchdog_timeout(args.watchdog)

    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    store = mpx.ShardStore(comm, bootstrap={
        "host": "localhost",
        "port_base": args.port_base,
        "process_id": args.process_id,
        "num_processes": args.num_processes,
        "agree_port_base": args.port_base + 100,
    })
    step_fn, losses = _make_elastic_step(mpx)

    state = {"params": _init_params()}
    state = mpx.elastic.run(step_fn, state, store, steps=args.steps,
                            commit_every=args.commit_every)

    final_world = int(store.comm.Get_size())
    if args.out:
        with open(f"{args.out}.p{args.process_id}", "w") as f:
            json.dump({"losses": losses, "final_world": final_world}, f,
                      indent=2)
    print(f"{DONE_TAG} steps={args.steps} world={final_world}", flush=True)


def run_launcher(args):
    """Spawn the N-process world, reap survivors, judge the drill.

    Success = a strict MAJORITY of workers exit 0 AND each of them
    printed the completion tag with the full step budget.  Workers killed
    by the fault injector (``die`` exits 13) or hung forever (``hang``,
    killed here once the survivors finish) are the drill's subjects, not
    failures of it.
    """
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port_base = s.getsockname()[1]

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    n = args.launch
    workers = []
    for i in range(n):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--steps", str(args.steps),
               "--commit-every", str(args.commit_every),
               "--process-id", str(i), "--num-processes", str(n),
               "--port-base", str(port_base),
               "--watchdog", str(args.watchdog)]
        if args.out:
            cmd += ["--out", args.out]
        workers.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))

    deadline = time.monotonic() + args.drill_timeout
    outputs = {}
    while time.monotonic() < deadline:
        live = [p for p in workers if p.poll() is None]
        done_ok = [p for p in workers
                   if p.poll() is not None and p.returncode == 0]
        if not live:
            break
        if len(done_ok) > n // 2:
            # the surviving majority finished; whoever is still running is
            # the drill's hung subject — give stragglers a grace period,
            # then put them down
            grace = time.monotonic() + 10.0
            while any(p.poll() is None for p in workers) \
                    and time.monotonic() < grace:
                time.sleep(0.2)
            for p in workers:
                if p.poll() is None:
                    p.kill()
            break
        time.sleep(0.5)
    for i, p in enumerate(workers):
        try:
            out, _ = p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outputs[i] = out or ""
        sys.stdout.write(f"--- worker {i} (exit {p.returncode}) ---\n")
        sys.stdout.write(outputs[i])
    winners = [i for i, p in enumerate(workers) if p.returncode == 0]
    completed = [i for i in winners
                 if f"{DONE_TAG} steps={args.steps}" in outputs[i]]
    print(f"drill: {len(completed)}/{n} workers completed the "
          f"{args.steps}-step budget: ranks {completed}", flush=True)
    if len(completed) > n // 2 and completed == winners:
        print("DRILL_OK", flush=True)
        return 0
    print("DRILL_FAILED", flush=True)
    return 1


def main(argv=None):
    args = _parse_args(argv)
    if args.launch > 0:
        return run_launcher(args)
    if args.process_id >= 0:
        run_worker(args)
        return 0
    run_single(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
