"""Elastic data-parallel training: survive rank loss and keep going.

The acceptance drill for the elastic-recovery layer
(docs/resilience.md "Elastic recovery" / "Grow and graceful drain"): a
DP-SGD loop wrapped in ``mpx.elastic.run`` with a ``ShardStore``
in-memory checkpoint.  When a rank dies (or hangs) mid-run, the
survivors agree on the failed set, revoke the communication epoch,
shrink the mesh/comm to "all minus failed", restore the last committed
state from the surviving shard replicas, and finish the step budget on
``k - f`` ranks.

Two modes:

- **single process** (default): all local devices form the world; a
  simulated :class:`RankFailure` fires at ``--fail-step`` and the mesh
  shrinks in place —

      python examples/elastic_training.py

- **multi-process drill** (``--launch N``): N worker processes (one CPU
  device each) over ``jax.distributed``; kill one with the fault
  injector and the survivors re-bootstrap a smaller world —

      MPI4JAX_TPU_FAULT_SPEC='die:rank=3:op=allreduce:after=5' \\
        python examples/elastic_training.py --launch 4 --steps 12

  The parent exits 0 iff a surviving majority completed the full step
  budget.  Swap ``die`` for ``hang`` to drill the watchdog-expiry
  detection path (the loop claims the expiry handler while it runs).

Elastic extensions (this file is also their CI drill):

- ``--grow``: after the fault injector kills a rank, the launcher
  spawns a REPLACEMENT process (``mpx.elastic.join_and_run``) that
  contacts the shrunken world's coordinator, is admitted at a commit
  boundary, receives the committed state through the cold-join restore,
  and helps finish the budget at the original world size — the 4→3→4
  loop.  Requires ``MPI4JAX_TPU_ELASTIC_GROW=1`` in the environment.
- ``--grid RxC``: run on a Cartesian (R, C) mesh.  Combined with a
  ``preempt`` fault clause and ``MPI4JAX_TPU_ELASTIC_FAIL_UNIT=row``,
  this is the graceful-preemption drill: the preempted rank's whole
  grid row drains out at a step boundary (one forced commit, one
  ``drain`` incident, zero watchdog expiries) and the remaining rows
  finish the budget —

      MPI4JAX_TPU_ELASTIC_FAIL_UNIT=row \\
      MPI4JAX_TPU_FAULT_SPEC='preempt:rank=3:after=4' \\
        python examples/elastic_training.py --launch 4 --grid 2x2 \\
          --steps 12 --expect-world 2
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

DONE_TAG = "ELASTIC_DONE"
DRAINED_TAG = "ELASTIC_DRAINED"


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=12,
                   help="total training steps to complete (the budget)")
    p.add_argument("--commit-every", type=int, default=1,
                   help="commit the state to the ShardStore every N steps")
    p.add_argument("--fail-step", type=int, default=5,
                   help="single-process mode: step at which the simulated "
                        "failure fires (<0 disables)")
    p.add_argument("--fail-rank", type=int, default=-1,
                   help="single-process mode: rank to fail (-1 = last)")
    p.add_argument("--out", default="",
                   help="write the per-step loss trace as JSON here")
    # multi-process drill plumbing
    p.add_argument("--launch", type=int, default=0, metavar="N",
                   help="launch an N-process world and run the drill")
    p.add_argument("--grow", action="store_true",
                   help="--launch parent: spawn a replacement worker "
                        "(join_and_run) for each rank the fault injector "
                        "kills — the shrink-then-grow drill (needs "
                        "MPI4JAX_TPU_ELASTIC_GROW=1)")
    p.add_argument("--grid", default="",
                   help="Cartesian mesh shape 'RxC' (default: 1-D world)")
    p.add_argument("--expect-world", type=int, default=0,
                   help="--launch parent: expected FINAL world size "
                        "(default: launch size minus fault subjects)")
    p.add_argument("--process-id", type=int, default=-1,
                   help=argparse.SUPPRESS)  # worker-internal
    p.add_argument("--num-processes", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--port-base", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--join", action="store_true",
                   help=argparse.SUPPRESS)  # replacement-worker-internal
    p.add_argument("--watchdog", type=float, default=30.0,
                   help="multi-process drill: watchdog timeout in seconds "
                        "(the hang-drill detection bound)")
    p.add_argument("--drill-timeout", type=float, default=540.0,
                   help="--launch parent: seconds before the drill fails")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# the model + elastic step (shared by both modes)
# ---------------------------------------------------------------------------


def _init_params(dim=16, hidden=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": rng.normal(0, dim ** -0.5, (dim, hidden)).astype(np.float32),
        "b1": np.zeros((hidden,), np.float32),
        "w2": rng.normal(0, hidden ** -0.5, (hidden, 1)).astype(np.float32),
        "b2": np.zeros((1,), np.float32),
    }


def _data_for(k, per_rank=32, dim=16, seed=1):
    """Synthetic regression data with a leading rank axis, derived from
    the CURRENT world size — after a shrink the survivors re-derive it
    at k-f (every process computes the same arrays: same seed)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(k, per_rank, dim)).astype(np.float32)
    w = rng.normal(size=(dim, 1)).astype(np.float32)
    y = np.tanh(x @ w).astype(np.float32)
    return x, y


def _make_elastic_step(mpx, lr=0.05, store=None):
    """``step_fn(state, step, comm)`` for ``mpx.elastic.run``: builds (and
    caches) one SPMD program per comm — after a shrink the new comm gets a
    fresh program traced at the new size (the epoch in the cache key
    guarantees the old one is unreachable anyway).

    The gradient exchange is ``mpx.compress.ef_allreduce`` with the
    error-feedback residual COMMITTED as part of the state (one row per
    rank): with ``MPI4JAX_TPU_COMPRESS=off`` it is the plain allreduce
    and the residual stays zero; under bf16/fp8 a restore replays the
    residual from the last commit, a shrink moves surviving rows to
    their new ranks (``store.last_rank_map`` -> ``ef_reshard``), and a
    cold joiner's row starts ZERO — never a dead rank's stale error
    (docs/compression.md "Error feedback under elasticity")."""
    import jax
    import jax.numpy as jnp

    programs = {}

    def train_step_for(comm):
        key = (comm.uid, comm.epoch)
        if key not in programs:
            size = comm.Get_size()

            @mpx.spmd(comm=comm)
            def train_step(params, residual, x, y):
                def loss_fn(p, x, y):
                    h = jax.nn.relu(x @ p["w1"] + p["b1"])
                    pred = h @ p["w2"] + p["b2"]
                    return jnp.mean((pred - y) ** 2)

                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                red, residual, token = mpx.compress.ef_allreduce(
                    grads, residual, op=mpx.SUM, comm=comm)
                loss = mpx.allreduce(loss, op=mpx.SUM, comm=comm,
                                     token=token)[0] / size
                new = jax.tree.map(lambda p, g: p - lr * (g / size),
                                   params, red)
                return mpx.varying((new, residual, loss))

            programs[key] = train_step
        return programs[key]

    def replicate(tree, k):
        return jax.tree.map(
            lambda v: jnp.tile(jnp.asarray(v)[None], (k,) + (1,) * v.ndim),
            tree)

    def residual_for(state, params_g, k):
        res = state.get("ef_residual")
        if res is None:
            return mpx.compress.ef_zeros_like(params_g)
        old_k = int(np.shape(jax.tree.leaves(res)[0])[0])
        if old_k == k:
            return res
        # a restore across a boundary: the committed residual's rows
        # belong to the OLD world — move survivors, zero joiners
        rmap = store.last_rank_map if store is not None else None
        if rmap is None:
            rmap = {r: r for r in range(min(old_k, k))}
        return mpx.compress.ef_reshard(res, rmap, k)

    losses = []

    def step_fn(state, step, comm):
        k = comm.Get_size()
        x, y = _data_for(k)
        params_g = replicate(state["params"], k)
        res = residual_for(state, params_g, k)
        params_g, res, loss = train_step_for(comm)(params_g, res, x, y)
        loss = float(np.asarray(loss)[0])
        losses.append({"step": step, "world": k, "loss": loss,
                       "epoch": comm.epoch})
        print(f"step {step:3d}  world {k}  epoch {comm.epoch}  "
              f"loss {loss:.6f}", flush=True)
        # params stay single-copy (replicated invariant: every rank's row
        # is identical, row 0 is the canonical copy the ShardStore
        # shards); the residual is genuinely per-rank, so its full
        # (k, ...) stack is the committed artifact
        return {"params": jax.tree.map(lambda v: np.asarray(v[0]), params_g),
                "ef_residual": jax.tree.map(np.asarray, res)}

    return step_fn, losses


# ---------------------------------------------------------------------------
# single-process mode: simulated failure, in-place mesh shrink
# ---------------------------------------------------------------------------


def run_single(args):
    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    k = comm.Get_size()
    fail_rank = args.fail_rank if args.fail_rank >= 0 else k - 1
    fail_at = args.fail_step if 0 <= args.fail_step < args.steps else None
    if fail_at is not None and k < 2:
        print("single device: nothing to shrink, running clean")
        fail_at = None

    store = mpx.ShardStore(comm)
    base_step, losses = _make_elastic_step(mpx, store=store)

    def step_fn(state, step, comm):
        state = base_step(state, step, comm)
        if fail_at is not None and step == fail_at and comm.epoch == 0:
            # simulate rank loss AFTER the step's work (a real death
            # surfaces as an error/expiry inside the next collective; the
            # recovery path from here on is identical)
            raise mpx.RankFailure({fail_rank},
                                  f"simulated loss of rank {fail_rank}")
        return state

    state = {"params": _init_params()}
    state = mpx.elastic.run(step_fn, state, store, steps=args.steps,
                            commit_every=args.commit_every)

    final_world = store.comm.Get_size()
    expect_world = k - 1 if fail_at is not None else k
    assert final_world == expect_world, (final_world, expect_world)
    assert len([r for r in losses if r["step"] == args.steps - 1]) == 1
    if fail_at is not None:
        from mpi4jax_tpu.resilience import elastic as el

        assert el.current_epoch() == 1, el.current_epoch()
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"losses": losses, "final_world": final_world}, f,
                      indent=2)
    print(f"{DONE_TAG} steps={args.steps} world={final_world}", flush=True)
    return state


# ---------------------------------------------------------------------------
# multi-process drill: --launch parent + worker halves
# ---------------------------------------------------------------------------


def _parse_grid(spec):
    if not spec:
        return None
    r, _, c = spec.lower().partition("x")
    return int(r), int(c)


def _make_mesh_comm(mpx, grid):
    if grid is None:
        mesh = mpx.make_world_mesh()
    else:
        mesh = mpx.make_world_mesh(grid, ("y", "x"))
    comm = mpx.Comm(tuple(mesh.axis_names), mesh=mesh)
    return mesh, comm


def _finish_worker(args, store, losses, pid):
    final_world = int(store.comm.Get_size())
    if args.out:
        with open(f"{args.out}.p{pid}", "w") as f:
            json.dump({"losses": losses, "final_world": final_world,
                       "drained": bool(store.drained)}, f, indent=2)
    if store.drained:
        # shrunk out by a planned drain (the preempted rank, or a
        # row-mate on a Cartesian drain): a graceful exit, not a
        # completion — the survivors own the rest of the budget
        print(f"{DRAINED_TAG} world={final_world}", flush=True)
    else:
        print(f"{DONE_TAG} steps={args.steps} world={final_world}",
              flush=True)


def run_worker(args):
    import jax

    import mpi4jax_tpu as mpx

    mpx.init_distributed(
        coordinator_address=f"localhost:{args.port_base}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.device_count() == args.num_processes

    if args.watchdog > 0:
        mpx.set_watchdog_timeout(args.watchdog)

    _, comm = _make_mesh_comm(mpx, _parse_grid(args.grid))
    store = mpx.ShardStore(comm, bootstrap={
        "host": "localhost",
        "port_base": args.port_base,
        "process_id": args.process_id,
        "num_processes": args.num_processes,
        "agree_port_base": args.port_base + 100,
    })
    step_fn, losses = _make_elastic_step(mpx, store=store)

    state = {"params": _init_params()}
    state = mpx.elastic.run(step_fn, state, store, steps=args.steps,
                            commit_every=args.commit_every)
    _finish_worker(args, store, losses, args.process_id)


def run_joiner(args):
    """A replacement worker: contact the running (shrunken) world's
    coordinator, get admitted at a commit boundary, receive the
    committed state through the cold-join restore, and help finish the
    budget (docs/resilience.md "Grow and graceful drain")."""
    import mpi4jax_tpu as mpx

    if args.watchdog > 0:
        mpx.set_watchdog_timeout(args.watchdog)

    store = mpx.ShardStore(None, bootstrap={
        "host": "localhost",
        "port_base": args.port_base,
        "agree_port_base": args.port_base + 100,
    })
    step_fn, losses = _make_elastic_step(mpx, store=store)
    mpx.elastic.join_and_run(step_fn, store, steps=args.steps,
                             commit_every=args.commit_every,
                             join_timeout=args.drill_timeout)
    _finish_worker(args, store, losses,
                   f"j{store.bootstrap['process_id']}")


def run_launcher(args):
    """Spawn the N-process world, reap survivors, judge the drill.

    Success = the expected number of workers (``--expect-world``, or a
    strict MAJORITY by default) exit 0 with the completion tag and the
    full step budget, and every OTHER exit-0 worker was gracefully
    drained (the ``preempt`` drill's leavers print the drained tag).
    Workers killed by the fault injector (``die`` exits 13) or hung
    forever (``hang``, killed here once the survivors finish) are the
    drill's subjects, not failures of it.  With ``--grow``, each killed
    worker is replaced by a joiner (``join_and_run``) that must ALSO
    complete — the shrink-then-grow loop.
    """
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port_base = s.getsockname()[1]

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    if args.grow:
        env["MPI4JAX_TPU_ELASTIC_GROW"] = "1"
    n = args.launch

    def common_flags():
        cmd = ["--steps", str(args.steps),
               "--commit-every", str(args.commit_every),
               "--port-base", str(port_base),
               "--watchdog", str(args.watchdog),
               "--drill-timeout", str(args.drill_timeout)]
        if args.grid:
            cmd += ["--grid", args.grid]
        if args.out:
            cmd += ["--out", args.out]
        return cmd

    def spawn(extra, name):
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + common_flags()
            + extra,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        proc._drill_name = name
        return proc

    workers = [
        spawn(["--process-id", str(i), "--num-processes", str(n)], f"r{i}")
        for i in range(n)
    ]
    # a joiner cannot start with its replacement target still alive (the
    # fault has not fired yet): spawned on first observed subject death
    spawned = 0
    target = args.expect_world if args.expect_world > 0 else n // 2 + 1

    deadline = time.monotonic() + args.drill_timeout
    while time.monotonic() < deadline:
        subjects = [p for p in workers
                    if p.poll() is not None and p.returncode != 0]
        if args.grow and len(subjects) > spawned:
            for _ in range(len(subjects) - spawned):
                workers.append(spawn(["--join"], f"j{spawned}"))
                spawned += 1
        live = [p for p in workers if p.poll() is None]
        done_ok = [p for p in workers
                   if p.poll() is not None and p.returncode == 0]
        if not live:
            break
        if len(done_ok) >= target:
            # the expected completions are in; whoever is still running
            # is the drill's hung subject — give stragglers a grace
            # period, then put them down
            grace = time.monotonic() + 20.0
            while any(p.poll() is None for p in workers) \
                    and time.monotonic() < grace:
                time.sleep(0.2)
            for p in workers:
                if p.poll() is None:
                    p.kill()
            break
        time.sleep(0.5)
    outputs = {}
    for p in workers:
        name = p._drill_name
        try:
            out, _ = p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outputs[name] = (p.returncode, out or "")
        sys.stdout.write(f"--- worker {name} (exit {p.returncode}) ---\n")
        sys.stdout.write(outputs[name][1])
    winners = [nm for nm, (rc, _) in outputs.items() if rc == 0]
    completed = [nm for nm in winners
                 if f"{DONE_TAG} steps={args.steps}" in outputs[nm][1]]
    drained = [nm for nm in winners if DRAINED_TAG in outputs[nm][1]]
    print(f"drill: {len(completed)} worker(s) completed the "
          f"{args.steps}-step budget ({completed}), {len(drained)} "
          f"drained gracefully ({drained})", flush=True)
    ok = len(completed) >= target
    # every exit-0 worker must be accounted for: a completion or a
    # graceful drain — an exit-0 worker with neither tag went wrong
    ok = ok and sorted(winners) == sorted(set(completed) | set(drained))
    if args.expect_world > 0:
        ok = ok and len(completed) == args.expect_world
    if ok:
        print("DRILL_OK", flush=True)
        return 0
    print("DRILL_FAILED", flush=True)
    return 1


def main(argv=None):
    args = _parse_args(argv)
    if args.launch > 0:
        return run_launcher(args)
    if args.join:
        run_joiner(args)
        return 0
    if args.process_id >= 0:
        run_worker(args)
        return 0
    run_single(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
