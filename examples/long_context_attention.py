"""Long-context attention demo.

The implementation is first-class package API —
``mpi4jax_tpu.attention`` (ring + Ulysses sequence parallelism with
O(T/n)-memory custom-VJP backward, built on the fused flash kernels) —
re-exported here so the example/tests read naturally; this file adds the
runnable demo.  See docs/long_context.md.
"""

import os
import sys
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.attention import (  # noqa: E402,F401
    flash_attention,
    reference_attention,
    ring_attention,
    ulysses_attention,
)


@partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _demo_data(key, size, b, t_loc, h, d):
    ks = jax.random.split(key, 3)
    shape = (size, b, t_loc, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def main():
    devices = jax.devices()
    n = len(devices)
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    # ulysses shards heads across devices, so h must be a multiple of n
    b, t_loc, h, d = 2, 128, n * max(1, 8 // n), 64
    q, k, v = _demo_data(jax.random.PRNGKey(0), n, b, t_loc, h, d)

    @mpx.spmd(comm=comm)
    def ring(q, k, v):
        return ring_attention(q, k, v, comm=comm, causal=True)

    out = ring(q, k, v)
    print(f"ring attention over {n} devices: global T = {n * t_loc}, "
          f"local out {out.shape[1:]} ok")

    @mpx.spmd(comm=comm)
    def uly(q, k, v):
        return ulysses_attention(q, k, v, comm=comm, causal=True)

    out = uly(q, k, v)
    print(f"ulysses attention over {n} devices: ok {out.shape[1:]}")


if __name__ == "__main__":
    main()
