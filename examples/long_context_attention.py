"""Long-context attention on the communication primitives.

The reference contains no sequence parallelism (SURVEY.md §5) — but its
primitive set is exactly what the standard long-context schemes are built
from.  This module implements both standard schemes TPU-natively on
mpi4jax_tpu's primitives, as executable documentation that the primitives
compose into sequence/context parallelism:

- **ring attention** (blockwise attention over a `sendrecv` ring;
  Liu et al. 2023): each rank holds a sequence shard of K/V and rotates it
  around the ring with ``shift(1)`` — one CollectivePermute per step over
  ICI — accumulating attention with a streaming (flash-style) softmax.
  Memory per chip stays O(T/n), enabling sequences n× longer than one chip
  could hold; compute overlaps the permutes (XLA pipelines the unrolled
  steps).  Causal runs compute only the visible blocks (fully-masked ring
  steps are skipped per rank via ``lax.cond``; fully-visible blocks skip
  masking) — n(n+1)/2 blocks of MXU work instead of n², measured 2.10×
  end-to-end on the 8-rank test mesh — and the diagonal block uses the
  key-tile-skipping causal kernel (1.66× that block on TPU, see
  kernels/flash_attention.py).
- **Ulysses-style attention** (`alltoall` head exchange; Jacobs et al.
  2023): two all-to-alls re-shard from sequence-parallel to head-parallel
  and back, with full-sequence local attention in between.

Both are exact (not approximations) and match single-device attention to
f32 precision — see tests/test_long_context.py.
"""

import math
import os
import sys
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.experimental import notoken  # noqa: E402
from mpi4jax_tpu.kernels.flash_attention import (  # noqa: E402
    flash_block_partials,
    merge_partials,
)


def reference_attention(q, k, v, *, causal=False):
    """Plain full attention (B, T, H, D) — the single-device ground truth."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, *, comm=None, causal=False):
    """Exact blockwise attention over a K/V ring.

    ``q``/``k``/``v``: rank-local sequence shards ``(B, T_local, H, D)``;
    the global sequence is the rank-order concatenation.  Returns the local
    shard of the attention output.  Call inside a parallel region.

    The per-block attention partials come from
    ``mpi4jax_tpu.kernels.flash_attention``: the fused Pallas kernel on TPU
    (the (Tq, Tk) score matrix never leaves VMEM), the identical-math jnp
    path elsewhere; ``merge_partials`` is the flash combine rule across
    ring steps.
    """
    comm = comm if comm is not None else mpx.get_default_comm()
    size = comm.Get_size()
    rank = comm.Get_rank()
    b, t_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    # streaming-softmax accumulators (flash-attention style)
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    acc = jnp.zeros_like(q)
    # promote fresh (replicated-typed) constants so they can join the
    # varying carry (docs/sharp_bits.md)
    m, l, acc = mpx.varying((m, l, acc))

    k_blk, v_blk = k, v
    # static unroll: `size` steps, each one CollectivePermute + one block of
    # MXU work — XLA pipelines compute with the permutes
    for step in range(size):
        # k_blk currently holds the shard originally owned by src = rank -
        # step (mod size).  Causal block taxonomy (block granularity, exact):
        #   step == 0  (src == rank):  the diagonal block — triangular mask;
        #   step <= rank (src < rank): every key precedes every query —
        #       fully visible, compute UNMASKED (no mask load/selects);
        #   step >  rank (src > rank): every key follows every query —
        #       fully masked, skip the block's compute entirely.
        # `rank` is a traced per-device value (SPMD traces one program), so
        # the skip is a lax.cond: ranks take the identity branch at run
        # time instead of computing a block that masking would zero out.
        # This halves total causal ring FLOPs (sum over ranks: n(n+1)/2
        # useful blocks vs n^2 computed blocks before).
        if causal and step == 0:
            # diagonal block: global offsets cancel — declare the triangle
            # structurally so the TPU kernel can SKIP the fully-masked key
            # tiles (~1.7x on this block) instead of masking computed scores
            o_new, m_new, l_new = flash_block_partials(
                q, k_blk, v_blk, None, scale=scale, causal=True
            )
            acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)
        elif causal:

            def _attend(carry, kb=k_blk, vb=v_blk):
                acc, m, l = carry
                o_new, m_new, l_new = flash_block_partials(
                    q, kb, vb, None, scale=scale
                )
                return merge_partials(acc, m, l, o_new, m_new, l_new)

            acc, m, l = jax.lax.cond(
                step <= rank, _attend, lambda carry: carry, (acc, m, l)
            )
        else:
            o_new, m_new, l_new = flash_block_partials(
                q, k_blk, v_blk, None, scale=scale
            )
            acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)

        if step + 1 < size:
            # rotate K/V one hop around the ring (tokenless: the data
            # dependency on k_blk/v_blk already orders the permute)
            k_blk = notoken.sendrecv(k_blk, k_blk, dest=mpx.shift(1), comm=comm)
            v_blk = notoken.sendrecv(v_blk, v_blk, dest=mpx.shift(1), comm=comm)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    # merge accumulates in f32; return in the input dtype
    return (acc / jnp.moveaxis(l_safe, 1, 2)[..., None]).astype(q.dtype)


def flash_attention(q, k, v, causal=False):
    """Single-device attention via the fused flash kernel: block partials +
    normalization, so the (T, T) score matrix never reaches HBM (the
    ``reference_attention`` einsum materializes it).  Causal uses the
    key-tile-skipping kernel on TPU; non-causal streams (512, 512) key
    tiles with online-softmax carries, so the live score tile is fixed-
    size regardless of sequence length — the VMEM ceiling is the K/V
    residency (~2·T·D·itemsize, about 90k f32 tokens at D=128), not T².

    Differentiable on every backend: ``flash_block_partials`` carries a
    blockwise custom VJP (Pallas backward kernels on TPU), so gradients
    match ``reference_attention``'s without ever materializing the score
    matrix — forward or backward.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, _, l = flash_block_partials(q, k, v, None, scale=scale, causal=causal)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / jnp.moveaxis(l_safe, 1, 2)[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, *, comm=None, causal=False):
    """Exact attention via all-to-all head exchange (Ulysses).

    Input shards ``(B, T_local, H, D)`` with ``H % size == 0``: re-shard to
    ``(B, T_global, H/size, D)`` with one ``alltoall``, run full-sequence
    local flash attention on the head group (fused kernel — the global
    score matrix never hits HBM), and re-shard back.
    """
    comm = comm if comm is not None else mpx.get_default_comm()
    size = comm.Get_size()
    b, t_loc, h, d = q.shape
    if h % size != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by ranks ({size})")
    h_loc = h // size

    def seq_to_heads(x):
        # (B, T_l, H, D) -> alltoall rows = head groups -> (B, T_g, H/size, D)
        x = x.reshape(b, t_loc, size, h_loc, d).transpose(2, 0, 1, 3, 4)
        x = notoken.alltoall(x, comm=comm)  # row i: rank i's T_l for my heads
        return x.transpose(1, 0, 2, 3, 4).reshape(b, size * t_loc, h_loc, d)

    def heads_to_seq(x):
        # (B, T_g, H/size, D) -> (B, T_l, H, D)
        x = x.reshape(b, size, t_loc, h_loc, d).transpose(1, 0, 2, 3, 4)
        x = notoken.alltoall(x, comm=comm)
        return x.transpose(1, 2, 0, 3, 4).reshape(b, t_loc, h, d)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(qh, kh, vh, causal)
    return heads_to_seq(out)


@partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _demo_data(key, size, b, t_loc, h, d):
    ks = jax.random.split(key, 3)
    shape = (size, b, t_loc, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def main():
    devices = jax.devices()
    n = len(devices)
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    # ulysses shards heads across devices, so h must be a multiple of n
    b, t_loc, h, d = 2, 128, n * max(1, 8 // n), 64
    q, k, v = _demo_data(jax.random.PRNGKey(0), n, b, t_loc, h, d)

    @mpx.spmd(comm=comm)
    def ring(q, k, v):
        return ring_attention(q, k, v, comm=comm, causal=True)

    out = ring(q, k, v)
    print(f"ring attention over {n} devices: global T = {n * t_loc}, "
          f"local out {out.shape[1:]} ok")

    @mpx.spmd(comm=comm)
    def uly(q, k, v):
        return ulysses_attention(q, k, v, comm=comm, causal=True)

    out = uly(q, k, v)
    print(f"ulysses attention over {n} devices: ok {out.shape[1:]}")


if __name__ == "__main__":
    main()
