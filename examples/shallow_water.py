"""Shallow-water demo — the flagship end-to-end workload.

A nonlinear shallow-water solver on an Arakawa C-grid (energy-conserving
Sadourny scheme, the same physics as the reference demo, which adapts
https://github.com/dionhaefner/shallow-water), re-designed TPU-native.

Where the reference runs one MPI process per subdomain and threads tokens
through per-process ``send``/``recv``/``sendrecv`` calls
(ref /root/reference/examples/shallow_water.py:57-67, 173-271), this version
traces ONE SPMD program over a 2-D device mesh ``("py", "px")``:

- the state lives in *stacked-block* global arrays of shape
  ``(nproc, ny_local, nx_local)`` — rank ``r``'s subdomain (1-cell halo
  included) is ``state[r]`` — sharded over the mesh;
- each halo exchange is a ``sendrecv`` with a static ``shift`` routing on a
  row/column sub-communicator, lowering to a single CollectivePermute over
  ICI per direction (4 per field update vs the reference's ~4 p2p calls,
  but with no host round-trip and no descriptor marshalling);
- the time loop is a ``lax.fori_loop`` *inside* the region, so a whole
  multistep (10 model steps ≈ 40 collectives) is one XLA program that the
  compiler schedules and overlaps.

Usage:

    python shallow_water.py                     # demo, all local devices
    python shallow_water.py --benchmark         # reference benchmark config
    python shallow_water.py --save-animation    # write shallow-water.gif

(plain ``python`` — no ``mpirun``; multi-host pods via
``mpi4jax_tpu.init_distributed()``.)
"""

import argparse
import math
import os
import sys
import time
from dataclasses import dataclass, replace
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx
from mpi4jax_tpu import shift

DAY_IN_SECONDS = 86_400


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Config:
    """Model configuration (defaults = the reference demo's parameters,
    ref examples/shallow_water.py:69-135)."""

    # interior grid points (without the 1-cell overlap border)
    nx: int = 360
    ny: int = 180
    # grid spacing [m]
    dx: float = 5e3
    dy: float = 5e3
    # physics
    gravity: float = 9.81
    depth: float = 100.0
    coriolis_f: float = 2e-4
    coriolis_beta: float = 2e-11
    periodic_x: bool = True
    # Adams-Bashforth coefficients
    ab_a: float = 1.5 + 0.1
    ab_b: float = -(0.5 + 0.1)
    # process grid
    nproc_y: int = 1
    nproc_x: int = 1

    @property
    def lateral_viscosity(self) -> float:
        return 1e-3 * self.coriolis_f * self.dx**2

    @property
    def dt(self) -> float:
        # CFL-limited gravity-wave time step
        return 0.125 * min(self.dx, self.dy) / math.sqrt(self.gravity * self.depth)

    @property
    def nproc(self) -> int:
        return self.nproc_y * self.nproc_x

    @property
    def ny_local(self) -> int:
        assert self.ny % self.nproc_y == 0, "nproc_y must divide ny"
        return self.ny // self.nproc_y + 2  # +2 halo cells

    @property
    def nx_local(self) -> int:
        assert self.nx % self.nproc_x == 0, "nproc_x must divide nx"
        return self.nx // self.nproc_x + 2

    @property
    def length_x(self) -> float:
        return self.nx * self.dx

    @property
    def length_y(self) -> float:
        return self.ny * self.dy


class State(NamedTuple):
    """Stacked-block model state: every field is ``(nproc, ny_l, nx_l)``
    globally / ``(ny_l, nx_l)`` rank-local inside the region."""

    h: jax.Array
    u: jax.Array
    v: jax.Array
    dh: jax.Array
    du: jax.Array
    dv: jax.Array


def make_mesh_and_comm(cfg: Config, devices=None):
    """2-D device mesh ``(py, px)`` + communicator over both axes."""
    mesh = mpx.make_world_mesh(
        (cfg.nproc_y, cfg.nproc_x), ("py", "px"), devices=devices
    )
    return mesh, mpx.Comm(("py", "px"), mesh=mesh)


# ---------------------------------------------------------------------------
# initial conditions (host-side, decomposition-independent)
# ---------------------------------------------------------------------------


def initial_state(cfg: Config) -> State:
    """Geostrophically-balanced zonal jet + perturbation (the reference's
    IC, ref examples/shallow_water.py:138-170), computed globally on the
    host with numpy — identical for every decomposition — then cut into
    stacked local blocks."""
    # global coordinates including the 1-cell border, cell (1,1) at (0,0)
    x = (np.arange(cfg.nx + 2) - 1.0) * cfg.dx
    y = (np.arange(cfg.ny + 2) - 1.0) * cfg.dy
    yy, xx = np.meshgrid(y, x, indexing="ij")

    u0 = 10 * np.exp(-((yy - 0.5 * cfg.length_y) ** 2) / (0.02 * cfg.length_x) ** 2)
    v0 = np.zeros_like(u0)
    # approximate geostrophic balance: h_y = -(f/g) u
    f = cfg.coriolis_f + yy * cfg.coriolis_beta
    h_geo = np.cumsum(-cfg.dy * u0 * f / cfg.gravity, axis=0)
    h0 = (
        cfg.depth
        + h_geo
        - h_geo.mean()
        + 0.2
        * np.sin(xx / cfg.length_x * 10 * np.pi)
        * np.cos(yy / cfg.length_y * 8 * np.pi)
    )

    def cut(arr):
        blocks = []
        step_y, step_x = cfg.ny_local - 2, cfg.nx_local - 2
        for py in range(cfg.nproc_y):
            for px in range(cfg.nproc_x):
                blocks.append(
                    arr[
                        py * step_y : py * step_y + cfg.ny_local,
                        px * step_x : px * step_x + cfg.nx_local,
                    ]
                )
        return jnp.asarray(np.stack(blocks), dtype=jnp.float32)

    zeros = jnp.zeros((cfg.nproc, cfg.ny_local, cfg.nx_local), jnp.float32)
    return State(h=cut(h0), u=cut(u0), v=cut(v0), dh=zeros, du=zeros, dv=zeros)


def reassemble(stacked: np.ndarray, cfg: Config) -> np.ndarray:
    """Stacked local blocks ``(nproc, ny_l, nx_l)`` → global interior
    ``(ny, nx)`` (the analog of the reference's vmapped ``reassemble_array``,
    ref examples/shallow_water.py:475-490)."""
    interior = np.asarray(stacked)[:, 1:-1, 1:-1]
    ny_i, nx_i = interior.shape[1:]
    grid = interior.reshape(cfg.nproc_y, cfg.nproc_x, ny_i, nx_i)
    return grid.transpose(0, 2, 1, 3).reshape(cfg.nproc_y * ny_i, cfg.nproc_x * nx_i)


# ---------------------------------------------------------------------------
# halo exchange (runs inside the parallel region)
# ---------------------------------------------------------------------------


def enforce_boundaries(arr, kind: str, cfg: Config, comm: mpx.Comm, token):
    """Exchange the 1-cell halo with the four neighbors + apply physical
    boundary conditions.

    Replaces the reference's per-process send/recv/sendrecv ladder
    (ref examples/shallow_water.py:173-271): each direction is one
    ``sendrecv`` with a ``shift`` routing on the row (px) or column (py)
    sub-communicator — a single CollectivePermute over ICI, with edge ranks
    (``wrap=False``) keeping their current halo (MPI_PROC_NULL semantics).
    """
    assert kind in ("h", "u", "v")
    commx = comm.sub("px")
    commy = comm.sub("py")
    wrap_x = cfg.periodic_x

    # (what to send, where received data lands, sub-comm, routing)
    exchanges = (
        # west-to-east halo fill: rank r sends col 1 to r-1, writes col -1
        (np.s_[:, 1], np.s_[:, -1], commx, shift(-1, wrap=wrap_x)),
        # south-to-north: rank r sends row -2 to r+1, writes row 0
        (np.s_[-2, :], np.s_[0, :], commy, shift(+1, wrap=False)),
        # east-to-west: rank r sends col -2 to r+1, writes col 0
        (np.s_[:, -2], np.s_[:, 0], commx, shift(+1, wrap=wrap_x)),
        # north-to-south: rank r sends row 1 to r-1, writes row -1
        (np.s_[1, :], np.s_[-1, :], commy, shift(-1, wrap=False)),
    )
    for send_sel, recv_sel, c, route in exchanges:
        if c.Get_size() == 1 and not route.wrap:
            continue  # no neighbor anywhere along this direction
        received, token = mpx.sendrecv(
            arr[send_sel], arr[recv_sel], dest=route, comm=c, token=token
        )
        arr = arr.at[recv_sel].set(received)

    # physical (non-periodic) walls: no normal flow through the boundary
    if not cfg.periodic_x and kind == "u":
        on_east_wall = jax.lax.axis_index("px") == cfg.nproc_x - 1
        arr = arr.at[:, -2].set(jnp.where(on_east_wall, 0.0, arr[:, -2]))
    if kind == "v":
        on_north_wall = jax.lax.axis_index("py") == cfg.nproc_y - 1
        arr = arr.at[-2, :].set(jnp.where(on_north_wall, 0.0, arr[-2, :]))

    return arr, token


# ---------------------------------------------------------------------------
# model physics (runs inside the parallel region)
# ---------------------------------------------------------------------------


def local_coriolis(cfg: Config):
    """Coriolis parameter on this rank's rows, from the mesh coordinate
    (traced): y = (py * (ny_local-2) + j - 1) * dy."""
    py = jax.lax.axis_index("py")
    j = jnp.arange(cfg.ny_local)
    y = (py * (cfg.ny_local - 2) + j - 1.0) * cfg.dy
    return (cfg.coriolis_f + y * cfg.coriolis_beta)[:, None]


def model_step(state: State, cfg: Config, comm: mpx.Comm, first_step: bool) -> State:
    """One shallow-water step (Sadourny energy-conserving scheme +
    Adams-Bashforth 2), rank-local view.  Physics parity with ref
    examples/shallow_water.py:277-412."""
    token = mpx.create_token()
    h, u, v, dh, du, dv = state
    inner = np.s_[1:-1, 1:-1]
    dx, dy, g = cfg.dx, cfg.dy, cfg.gravity

    # cell-centered height with refreshed halo
    hc = jnp.pad(h[inner], 1, "edge")
    hc, token = enforce_boundaries(hc, "h", cfg, comm, token)

    # volume fluxes through east and north cell faces
    fe = jnp.zeros_like(u).at[inner].set(
        0.5 * (hc[1:-1, 1:-1] + hc[1:-1, 2:]) * u[inner]
    )
    fn = jnp.zeros_like(v).at[inner].set(
        0.5 * (hc[1:-1, 1:-1] + hc[2:, 1:-1]) * v[inner]
    )
    fe, token = enforce_boundaries(fe, "u", cfg, comm, token)
    fn, token = enforce_boundaries(fn, "v", cfg, comm, token)

    # continuity: dh/dt = -div(flux)
    dh_new = dh.at[inner].set(
        -(fe[1:-1, 1:-1] - fe[1:-1, :-2]) / dx - (fn[1:-1, 1:-1] - fn[:-2, 1:-1]) / dy
    )

    # potential vorticity q = (f + rel. vorticity) / interpolated depth
    coriolis = local_coriolis(cfg)
    rel_vort = (v[1:-1, 2:] - v[1:-1, 1:-1]) / dx - (u[2:, 1:-1] - u[1:-1, 1:-1]) / dy
    depth_q = 0.25 * (hc[1:-1, 1:-1] + hc[1:-1, 2:] + hc[2:, 1:-1] + hc[2:, 2:])
    q = jnp.zeros_like(h).at[inner].set(
        (coriolis[inner[0]] + rel_vort) / depth_q
    )
    q, token = enforce_boundaries(q, "h", cfg, comm, token)

    # momentum tendencies: pressure gradient + vorticity flux
    du_new = du.at[inner].set(
        -g * (h[1:-1, 2:] - h[1:-1, 1:-1]) / dx
        + 0.5
        * (
            q[1:-1, 1:-1] * 0.5 * (fn[1:-1, 1:-1] + fn[1:-1, 2:])
            + q[:-2, 1:-1] * 0.5 * (fn[:-2, 1:-1] + fn[:-2, 2:])
        )
    )
    dv_new = dv.at[inner].set(
        -g * (h[2:, 1:-1] - h[1:-1, 1:-1]) / dy
        - 0.5
        * (
            q[1:-1, 1:-1] * 0.5 * (fe[1:-1, 1:-1] + fe[2:, 1:-1])
            + q[1:-1, :-2] * 0.5 * (fe[1:-1, :-2] + fe[2:, :-2])
        )
    )

    # kinetic-energy gradient (C-grid average)
    ke = jnp.zeros_like(h).at[inner].set(
        0.5
        * (
            0.5 * (u[1:-1, 1:-1] ** 2 + u[1:-1, :-2] ** 2)
            + 0.5 * (v[1:-1, 1:-1] ** 2 + v[:-2, 1:-1] ** 2)
        )
    )
    ke, token = enforce_boundaries(ke, "h", cfg, comm, token)
    du_new = du_new.at[inner].add(-(ke[1:-1, 2:] - ke[1:-1, 1:-1]) / dx)
    dv_new = dv_new.at[inner].add(-(ke[2:, 1:-1] - ke[1:-1, 1:-1]) / dy)

    # time integration: forward Euler on the first step, AB-2 after
    if first_step:
        h = h.at[inner].add(cfg.dt * dh_new[inner])
        u = u.at[inner].add(cfg.dt * du_new[inner])
        v = v.at[inner].add(cfg.dt * dv_new[inner])
    else:
        h = h.at[inner].add(cfg.dt * (cfg.ab_a * dh_new[inner] + cfg.ab_b * dh[inner]))
        u = u.at[inner].add(cfg.dt * (cfg.ab_a * du_new[inner] + cfg.ab_b * du[inner]))
        v = v.at[inner].add(cfg.dt * (cfg.ab_a * dv_new[inner] + cfg.ab_b * dv[inner]))

    h, token = enforce_boundaries(h, "h", cfg, comm, token)
    u, token = enforce_boundaries(u, "u", cfg, comm, token)
    v, token = enforce_boundaries(v, "v", cfg, comm, token)

    # lateral friction on u and v
    if cfg.lateral_viscosity > 0:
        visc = cfg.lateral_viscosity
        for name, field in (("u", u), ("v", v)):
            gx = jnp.zeros_like(field).at[inner].set(
                visc * (field[1:-1, 2:] - field[1:-1, 1:-1]) / dx
            )
            gy = jnp.zeros_like(field).at[inner].set(
                visc * (field[2:, 1:-1] - field[1:-1, 1:-1]) / dy
            )
            gx, token = enforce_boundaries(gx, "u", cfg, comm, token)
            gy, token = enforce_boundaries(gy, "v", cfg, comm, token)
            field = field.at[inner].add(
                cfg.dt
                * (
                    (gx[1:-1, 1:-1] - gx[1:-1, :-2]) / dx
                    + (gy[1:-1, 1:-1] - gy[:-2, 1:-1]) / dy
                )
            )
            if name == "u":
                u = field
            else:
                v = field

    return State(h, u, v, dh_new, du_new, dv_new)


def model_step_fast(state: State, cfg: Config, comm: mpx.Comm,
                    first_step: bool) -> State:
    """One shallow-water step, numerically equivalent to ``model_step`` but
    restructured for the TPU memory system (see tests/test_examples.py for
    the step-for-step equality check).

    Why ``model_step`` is slow on TPU: every derived field is built as
    ``zeros_like(x).at[inner].set(expr)`` (a misaligned interior
    dynamic-update-slice — measured ~3.7x slower than an aligned
    full-field op on v5e) and is halo-exchanged (13 exchange rounds per
    step), splitting the step into ~13 tiny fusion regions.

    This version exploits an algebraic fact: with *coherent halos* on the
    inputs (each halo cell holds exactly its neighbor's current interior
    value), a derived field computed **full-field** with periodic rolls
    reproduces, operand for operand, the halo values the reference would
    have *received from its neighbor* — because the neighbor computes its
    edge from the very same values that our halo cells already hold.  So
    ``fe``/``fn``/``q``/``ke`` and the viscous fluxes need **no exchange at
    all**; only the state (``h``, ``u``, ``v``) is exchanged — 5 rounds
    instead of 13 — and ``hc`` becomes a fused ``where`` (wall-rank edge
    replication), not an exchange.  Wall semantics (``wrap=False``
    directions keep a zero halo; no-flux wall rows) become iota masks that
    fuse into the arithmetic for free.  Everything between exchanges is one
    large, aligned, fusion-friendly XLA region.

    To keep the coherent-halo invariant, ``u``/``v`` are re-exchanged after
    the viscous update (the reference instead lets seam halos lag the
    viscous substep by one step).  The two programs therefore differ at
    subdomain seams by one viscosity substep of halo freshness — the same
    order as the reference's own decomposition variance (its results on
    (1,1) vs (2,4) grids differ by exactly this class of artifact).  The
    fast path's *own* decomposition invariance is exact to rounding; see
    tests/test_examples.py.
    """
    token = mpx.create_token()
    h, u, v, dh, du, dv = state
    dx, dy, g = cfg.dx, cfg.dy, cfg.gravity
    ny, nx = cfg.ny_local, cfg.nx_local

    # stencil reads as aligned full-field rolls: rm1x(a)[j,i] == a[j,i+1] …
    rm1x = lambda a: jnp.roll(a, -1, 1)  # noqa: E731
    rp1x = lambda a: jnp.roll(a, 1, 1)  # noqa: E731
    rm1y = lambda a: jnp.roll(a, -1, 0)  # noqa: E731
    rp1y = lambda a: jnp.roll(a, 1, 0)  # noqa: E731

    iy = jax.lax.broadcasted_iota(jnp.int32, (ny, nx), 0)
    on_south = jax.lax.axis_index("py") == 0
    on_north = jax.lax.axis_index("py") == cfg.nproc_y - 1
    # y-halo rows that enforce_boundaries would NOT fill (wrap=False edge
    # ranks keep the zeros of zeros_like): these must be 0 in every derived
    # field, exactly as in the reference
    kept_y_halo = (on_south & (iy == 0)) | (on_north & (iy == ny - 1))
    interior = (iy > 0) & (iy < ny - 1)
    ix = jax.lax.broadcasted_iota(jnp.int32, (ny, nx), 1)
    interior &= (ix > 0) & (ix < nx - 1)
    u_wall = None  # kind-"u" no-flow wall column (ref enforce_boundaries)
    if not cfg.periodic_x:
        on_west = jax.lax.axis_index("px") == 0
        on_east = jax.lax.axis_index("px") == cfg.nproc_x - 1
        kept_y_halo |= (on_west & (ix == 0)) | (on_east & (ix == nx - 1))
        u_wall = on_east & (ix == nx - 2)

    def derived(expr, extra_zero=None):
        """Mask a full-field derived quantity to reference halo semantics."""
        zero = kept_y_halo if extra_zero is None else (kept_y_halo | extra_zero)
        return jnp.where(zero, 0.0, expr)

    # cell-centered height: with h's halos coherent (the end-of-step
    # exchanges maintain this; the initial state ships it), the reference's
    # pad-then-exchange of hc reduces to edge replication at wall ranks —
    # a fused where, no exchange, no update-slice
    hc = jnp.where(
        on_south & (iy == 0),
        rm1y(h),  # rm1y(h)[0] == h[1]: the "edge" pad row
        jnp.where(on_north & (iy == ny - 1), rp1y(h), h),
    )
    if not cfg.periodic_x:
        hc = jnp.where(
            on_west & (ix == 0),
            rm1x(hc),
            jnp.where(on_east & (ix == nx - 1), rp1x(hc), hc),
        )

    # ---- derived fields: full-field, no exchanges (see docstring) -------
    fe = derived(0.5 * (hc + rm1x(hc)) * u, u_wall)
    # fn additionally gets the no-flux wall row (kind "v": row -2 zeroed on
    # the north rank, ref enforce_boundaries)
    fn = derived(0.5 * (hc + rm1y(hc)) * v, on_north & (iy == ny - 2))

    coriolis = local_coriolis(cfg)  # (ny, 1), all rows
    rel_vort = (rm1x(v) - v) / dx - (rm1y(u) - u) / dy
    depth_q = 0.25 * (hc + rm1x(hc) + rm1y(hc) + rm1y(rm1x(hc)))
    q = derived((coriolis + rel_vort) / depth_q)

    # roll/elementwise-commutation rewrites, bit-identical to the canonical
    # stencils — MUST stay in lockstep with _phase1_window (the halo-path
    # equality tests pin exactness between the two)
    u_sq, v_sq = u * u, v * v
    ke = derived(
        0.5 * (0.5 * (u_sq + rp1x(u_sq)) + 0.5 * (v_sq + rp1y(v_sq)))
    )

    # ---- tendencies (halos zeroed: matches zeros-initialized dh/du/dv) --
    dh_new = jnp.where(
        interior,
        -(fe - rp1x(fe)) / dx - (fn - rp1y(fn)) / dy,
        0.0,
    )
    fn_e = 0.5 * (fn + rm1x(fn))
    fe_n = 0.5 * (fe + rm1y(fe))
    du_new = jnp.where(
        interior,
        -g * (rm1x(h) - h) / dx
        + 0.5 * (q * fn_e + rp1y(q) * rp1y(fn_e))
        - (rm1x(ke) - ke) / dx,
        0.0,
    )
    dv_new = jnp.where(
        interior,
        -g * (rm1y(h) - h) / dy
        - 0.5 * (q * fe_n + rp1x(q) * rp1x(fe_n))
        - (rm1y(ke) - ke) / dy,
        0.0,
    )

    # ---- time integration (tendency halos are 0, so full-field adds
    # preserve the state halos exactly) --------------------------------
    if first_step:
        h = h + cfg.dt * dh_new
        u = u + cfg.dt * du_new
        v = v + cfg.dt * dv_new
    else:
        h = h + cfg.dt * (cfg.ab_a * dh_new + cfg.ab_b * dh)
        u = u + cfg.dt * (cfg.ab_a * du_new + cfg.ab_b * du)
        v = v + cfg.dt * (cfg.ab_a * dv_new + cfg.ab_b * dv)

    h, token = enforce_boundaries(h, "h", cfg, comm, token)
    u, token = enforce_boundaries(u, "u", cfg, comm, token)
    v, token = enforce_boundaries(v, "v", cfg, comm, token)

    # ---- lateral friction: viscous fluxes with locally-computed ghosts.
    # The flux across a subdomain face is computable on both sides from the
    # (valid) field halos with identical operands, so no gx/gy exchange is
    # needed — another 4 exchange rounds saved vs the reference.
    if cfg.lateral_viscosity > 0:
        visc = cfg.lateral_viscosity
        for name in ("u", "v"):
            field = u if name == "u" else v
            gx = derived(visc * (rm1x(field) - field) / dx, u_wall)
            gy = derived(
                visc * (rm1y(field) - field) / dy,
                on_north & (iy == ny - 2),  # kind "v" wall row
            )
            field = field + jnp.where(
                interior,
                cfg.dt * ((gx - rp1x(gx)) / dx + (gy - rp1y(gy)) / dy),
                0.0,
            )
            if name == "u":
                u = field
            else:
                v = field

        # restore the coherent-halo invariant for the next step (the
        # docstring's one deliberate divergence from the reference, which
        # leaves seam halos one viscous substep stale).  Kind "h": pure
        # halo refresh — the no-flow wall rows were already applied once
        # above and must not be re-zeroed after the viscous update.
        u, token = enforce_boundaries(u, "h", cfg, comm, token)
        v, token = enforce_boundaries(v, "h", cfg, comm, token)

    return State(h, u, v, dh_new, du_new, dv_new)


# ---------------------------------------------------------------------------
# Pallas single-kernel step (single-rank hot path)
# ---------------------------------------------------------------------------

_PBLK = 128  # output rows per grid step (multiple of 8: f32 sublane tile)
# margin rows each side are 8 * nsteps (one sublane tile per fused step;
# the per-step recompute chain depth, with viscosity, is ~5 rows)


def _margin_rows(nsteps: int) -> int:
    """Margin / exchange depth for ``nsteps`` fused steps: 8 rows/cols of
    validity per step (chain depth ~5), rounded up to a divisor of
    ``_PBLK`` (the block-margin index maps need ``mrg | _PBLK``).  The
    single source of this invariant for both the whole-step chunk kernels
    and the wide-halo path."""
    if not 1 <= nsteps <= 3:  # deeper fusion exceeds VMEM/compiler
        raise ValueError(f"fused step windows support 1..3 steps, got {nsteps}")
    m = 8 * nsteps
    while _PBLK % m:
        m += 8
    return m


def _window_fields(ins, nfields: int):
    """Assemble ``nfields`` row windows from [prev-margin, main,
    next-margin] block-ref triples — shared by every blocked kernel
    body."""
    return tuple(
        jnp.concatenate(
            [ins[3 * k][:], ins[3 * k + 1][:], ins[3 * k + 2][:]], axis=0
        )
        for k in range(nfields)
    )


def _rolls(roll, nr: int, nx: int):
    """The four stencil shifts as positive-shift rolls (``roll`` is
    ``pltpu.roll`` inside kernels, ``jnp.roll`` on the direct path — the
    two agree for positive shifts)."""
    rm1x = lambda a: roll(a, nx - 1, 1)  # noqa: E731  a[j, i+1]
    rp1x = lambda a: roll(a, 1, 1)  # noqa: E731      a[j, i-1]
    rm1y = lambda a: roll(a, nr - 1, 0)  # noqa: E731  a[j+1, i]
    rp1y = lambda a: roll(a, 1, 0)  # noqa: E731       a[j-1, i]
    return rm1x, rp1x, rm1y, rp1y


def _window_masks(cfg: Config, iy, ix, giy, gix, wide=False):
    """Shared wall/update masks for the phase windows (single source of
    truth — must mirror ``model_step_fast``'s mask algebra, which the
    equality tests pin): ``(derived, u_wall, wall_v, interior)``.

    ``derived(expr, extra=None)`` zeroes the halo rows/cols a real exchange
    would leave untouched; ``u_wall``/``wall_v`` are the no-flow wall
    masks; ``interior`` is the update mask.

    ``wide`` selects the wide-halo frame (``model_step_pallas_wide``):
    there every cell is computed exactly as its *owning* rank computes it,
    so the update mask tests DOMAIN-GLOBAL interiority (a seam cell is
    some rank's interior and is updated in place — the recomputed value is
    bit-identical to what an exchange would deliver), and the kept masks
    use inequalities so the beyond-wall garbage rows of the widened frame
    are zeroed in every derived field.  In the default frame the update
    mask tests LOCAL indices: the rank's own halo ring is excluded and
    later refreshed by a real exchange (or the periodic in-register fix).
    """
    nyl, nxl = cfg.ny_local, cfg.nx_local
    gy_n, gx_n = cfg.ny + 2, cfg.nx + 2

    u_wall = None  # kind-"u" no-flow wall column
    wall_v = giy == gy_n - 2  # kind-"v" no-flux row (extra mask)
    if wide:
        # kept uses inequalities so beyond-wall garbage rows of the widened
        # frame are zeroed too; for periodic x the widened columns beyond
        # the global extent are wrap images of far-side interior columns —
        # their owner updates them, so no x constraint enters the masks
        kept = (giy <= 0) | (giy >= gy_n - 1)
        interior = (giy >= 1) & (giy <= gy_n - 2)
        if not cfg.periodic_x:
            kept |= (gix <= 0) | (gix >= gx_n - 1)
            interior &= (gix >= 1) & (gix <= gx_n - 2)
            u_wall = gix == gx_n - 2
    else:
        kept = (giy == 0) | (giy == gy_n - 1)
        if not cfg.periodic_x:
            kept |= (gix == 0) | (gix == gx_n - 1)
            u_wall = gix == gx_n - 2
        interior = (iy > 0) & (iy < nyl - 1) & (ix > 0) & (ix < nxl - 1)

    def derived(expr, extra=None):
        mask = kept if extra is None else (kept | extra)
        return jnp.where(mask, 0.0, expr)

    return derived, u_wall, wall_v, interior


def _phase1_window(cfg: Config, first_step: bool, iy, ix, giy, gix, fields,
                   roll, wide=False):
    """Integration phase of one model step (hc, fluxes, q, ke, tendencies,
    AB-2/Euler update) on a ``(nr, nx)`` row window, no exchanges.

    ``iy``/``ix`` are the cells' *rank-local* row/column indices (window
    margins included, so ``iy`` may exceed the local bounds); ``giy``/
    ``gix`` are the *domain-global* indices (``local + rank offset``) that
    all wall masks test against — on a single-rank decomposition the two
    coincide.  Requires the coherent-halo invariant on the input state
    (each halo cell holds its neighbor's current interior value); returns
    ``(h1, u1, v1, dh_new, du_new, dv_new)`` whose *local-interior* cells
    are valid — halo cells keep their (now stale) input values, exactly
    like ``model_step_fast`` before its mid-step exchange.  Margin rows
    within the recompute chain depth (~5) of the window edge are garbage
    that the caller's stored-slice keeps out.
    """
    h, u, v, dh, du, dv = fields
    nr, nx = h.shape
    gy_n, gx_n = cfg.ny + 2, cfg.nx + 2  # domain-global array heights
    dx, dy, g, dt = cfg.dx, cfg.dy, cfg.gravity, cfg.dt
    rm1x, rp1x, rm1y, rp1y = _rolls(roll, nr, nx)

    # wall masks test GLOBAL indices (on non-wall ranks a halo row/col maps
    # to a neighbor's interior index, so they are false there — its value
    # is then computed via rolls, valid by halo coherence); the update mask
    # tests LOCAL indices (every rank's own halo ring is excluded)
    derived, u_wall, wall_v, interior = _window_masks(
        cfg, iy, ix, giy, gix, wide
    )

    # hc: edge-replicated pad rows/cols at the physical walls; elsewhere
    # the (coherent) halo value is already the neighbor's interior
    hc = jnp.where(giy == 0, rm1y(h), jnp.where(giy == gy_n - 1, rp1y(h), h))
    if not cfg.periodic_x:
        hc = jnp.where(
            gix == 0, rm1x(hc), jnp.where(gix == gx_n - 1, rp1x(hc), hc)
        )

    fe = derived(0.5 * (hc + rm1x(hc)) * u, u_wall)
    fn = derived(0.5 * (hc + rm1y(hc)) * v, wall_v)

    cor = cfg.coriolis_f + (giy - 1).astype(jnp.float32) * cfg.dy * cfg.coriolis_beta
    rel_vort = (rm1x(v) - v) / dx - (rm1y(u) - u) / dy
    depth_q = 0.25 * (hc + rm1x(hc) + rm1y(hc) + rm1y(rm1x(hc)))
    q = derived((cor + rel_vort) / depth_q)
    # rolls are permutations, so they commute BIT-EXACTLY with elementwise
    # math: rp1x(u)**2 == rp1x(u*u), rp1y(a) + rp1y(b) == rp1y(a + b).
    # Rewriting the vorticity-flux and KE stencils through that identity
    # removes three rolls and two squarings per step at identical results
    # (roll is the most expensive VPU op here — see docs/shallow_water.md).
    u_sq, v_sq = u * u, v * v
    ke = derived(
        0.5 * (0.5 * (u_sq + rp1x(u_sq)) + 0.5 * (v_sq + rp1y(v_sq)))
    )

    dh_new = jnp.where(
        interior, -(fe - rp1x(fe)) / dx - (fn - rp1y(fn)) / dy, 0.0
    )
    fn_e = 0.5 * (fn + rm1x(fn))  # east-face vorticity-flux average
    fe_n = 0.5 * (fe + rm1y(fe))  # north-face average
    du_new = jnp.where(
        interior,
        -g * (rm1x(h) - h) / dx
        + 0.5 * (q * fn_e + rp1y(q) * rp1y(fn_e))
        - (rm1x(ke) - ke) / dx,
        0.0,
    )
    dv_new = jnp.where(
        interior,
        -g * (rm1y(h) - h) / dy
        - 0.5 * (q * fe_n + rp1x(q) * rp1x(fe_n))
        - (rm1y(ke) - ke) / dy,
        0.0,
    )

    if first_step:
        h1 = h + dt * dh_new
        u1 = u + dt * du_new
        v1 = v + dt * dv_new
    else:
        h1 = h + dt * (cfg.ab_a * dh_new + cfg.ab_b * dh)
        u1 = u + dt * (cfg.ab_a * du_new + cfg.ab_b * du)
        v1 = v + dt * (cfg.ab_a * dv_new + cfg.ab_b * dv)

    return h1, u1, v1, dh_new, du_new, dv_new


def _phase2_window(cfg: Config, iy, ix, giy, gix, u, v, roll, wide=False):
    """Viscosity phase of one model step on a window: lateral friction on
    ``u`` and ``v``, which must enter with *coherent halos* (the mid-step
    exchange / periodic fix).  Index conventions as ``_phase1_window``;
    recompute chain depth is 2 rows."""
    nr, nx = u.shape
    dx, dy, dt = cfg.dx, cfg.dy, cfg.dt
    rm1x, rp1x, rm1y, rp1y = _rolls(roll, nr, nx)
    derived, u_wall, wall_v, interior = _window_masks(
        cfg, iy, ix, giy, gix, wide
    )

    visc = cfg.lateral_viscosity
    out = []
    for f in (u, v):
        gx = derived(visc * (rm1x(f) - f) / dx, u_wall)
        gy = derived(visc * (rm1y(f) - f) / dy, wall_v)
        out.append(
            f
            + jnp.where(
                interior,
                dt * ((gx - rp1x(gx)) / dx + (gy - rp1y(gy)) / dy),
                0.0,
            )
        )
    return out[0], out[1]


def _step_window(cfg: Config, first_step: bool, n_rows: int, iy, ix, fields):
    """One WHOLE model step on a ``(nr, nx)`` row window, entirely in
    registers/VMEM: ``_phase1_window`` + in-register halo refreshes +
    ``_phase2_window``.

    Valid only for the single-rank, periodic-x decomposition (so global
    and local indices coincide — ``giy = iy``): x stencil reads use true
    periodic lane rolls, and every halo refresh (mid-step and end-of-step)
    becomes an in-register periodic column fix.  Multi-rank meshes use the
    split-phase path (``model_step_pallas_halo``), where the refreshes are
    real ``sendrecv`` exchanges between the phase kernels.
    """
    from jax.experimental.pallas import tpu as pltpu

    nx = fields[0].shape[1]

    def pc_fix(a):
        # periodic column refresh: col 0 <- col -2, col -1 <- col 1 (what
        # the single-rank wrap exchange delivers), fully in-register
        return jnp.where(
            ix == 0,
            pltpu.roll(a, 2, 1),
            jnp.where(ix == nx - 1, pltpu.roll(a, nx - 2, 1), a),
        )

    h1, u1, v1, dh_new, du_new, dv_new = _phase1_window(
        cfg, first_step, iy, ix, iy, ix, fields, pltpu.roll
    )

    # mid-step halo refresh (the jnp path's enforce_boundaries between
    # integration and viscosity): periodic column fix + kind-"v" wall row
    u1 = pc_fix(u1)
    v1 = jnp.where(iy == n_rows - 2, 0.0, pc_fix(v1))

    if cfg.lateral_viscosity > 0:
        u1, v1 = _phase2_window(cfg, iy, ix, iy, ix, u1, v1, pltpu.roll)

    # end-of-step halo refresh, in-register: on the single-rank periodic-x
    # decomposition the three enforce_boundaries(·, "h") exchanges reduce
    # exactly to the periodic column fix (col 0 <- col nx-2, col nx-1 <-
    # col 1, from the pre-fix array — bit-identical to the sendrecv pair),
    # so storing fixed ghosts saves three full-field HBM round-trips/step
    h1 = pc_fix(h1)
    u1 = pc_fix(u1)
    v1 = pc_fix(v1)

    return h1, u1, v1, dh_new, du_new, dv_new


def _sw_steps_kernel(cfg: Config, first_step: bool, n_rows: int, mrg: int,
                     nsteps: int, refs):
    """Kernel body: ``nsteps`` whole model steps on a
    ``(_PBLK + 2 * mrg, nx_local)`` row window, margins recomputed so no
    intermediate field — nor, for ``nsteps > 1``, the intermediate *state* —
    ever round-trips through HBM.  Each step consumes ~5 margin rows of
    validity (recompute chain depth), so ``mrg`` must be at least
    ``8 * nsteps`` (one sublane tile per step is ample).

    ``refs`` is 18 input refs (6 fields x [prev-margin, main, next-margin]
    blocks, field order h,u,v,dh,du,dv) followed by the 6 output refs; the
    unpacking below is positional by that structure.
    """
    import jax.experimental.pallas as pl

    ins, outs = refs[:18], refs[18:]
    nx = cfg.nx_local
    nr = _PBLK + 2 * mrg
    fields = _window_fields(ins, 6)

    pid = pl.program_id(0)
    iy = (
        jax.lax.broadcasted_iota(jnp.int32, (nr, nx), 0)
        + pid * _PBLK
        - mrg
    )
    ix = jax.lax.broadcasted_iota(jnp.int32, (nr, nx), 1)

    first = first_step
    for _ in range(nsteps):
        fields = _step_window(cfg, first, n_rows, iy, ix, fields)
        first = False

    sl = slice(mrg, mrg + _PBLK)
    for o, f in zip(outs, fields):
        o[:] = f[sl]


def _resolve_interpret(comm: mpx.Comm) -> bool:
    """Whether Pallas must run in interpret mode: resolve from the mesh the
    step actually runs on, not the process default backend (the two differ
    when a driver places the mesh on a non-default platform's devices)."""
    mesh = comm.mesh
    if mesh is not None and mesh.devices.size:
        return mesh.devices.flat[0].platform != "tpu"
    return jax.default_backend() != "tpu"


def _blocked_specs(ny: int, nx: int, mrg: int):
    """``(grid, main_spec, prev_spec, next_spec)`` for ``_PBLK``-row output
    blocks with ``mrg``-row recompute margins, clipped (duplicated) at the
    array edges — the margin-row mislabeling this causes only ever reaches
    rows that the wall masks zero or that no stored row reads (the same
    one-sided-read discipline that makes ``model_step_fast`` exchange-free
    for derived fields)."""
    import jax.experimental.pallas as pl

    grid = ((ny + _PBLK - 1) // _PBLK,)
    n_hblocks = (ny + mrg - 1) // mrg  # mrg-row halo block count
    r = _PBLK // mrg

    main = pl.BlockSpec((_PBLK, nx), lambda i: (i, 0))
    prev = pl.BlockSpec(
        (mrg, nx), lambda i: (jnp.clip(i * r - 1, 0, n_hblocks - 1), 0)
    )
    nxt = pl.BlockSpec(
        (mrg, nx), lambda i: (jnp.clip(i * r + r, 0, n_hblocks - 1), 0)
    )
    return grid, main, prev, nxt


def _tpu_compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    # at benchmark width (nx_local=3602) the 24 window blocks plus
    # kernel intermediates need most of the 100 MB granted here
    # (measured: _PBLK=256 needs 165 MB and overflows the chip's
    # 128 MB VMEM — raising _PBLK further requires shrinking the
    # working set first); Mosaic's default scoped limit is 16 MB
    return pltpu.CompilerParams(
        vmem_limit_bytes=100 * 1024 * 1024,
        dimension_semantics=("parallel",),
    )


def model_step_pallas(state: State, cfg: Config, comm: mpx.Comm,
                      first_step: bool, interpret=None,
                      nsteps: int = 1) -> State:
    """``nsteps`` applications of ``model_step_fast`` as ONE fused Pallas
    kernel — including every halo refresh, which on this path reduces to
    the in-register periodic column fix (see ``_step_window``), so there
    are no exchanges at all.

    Every intermediate (hc, fe, fn, q, ke, viscous fluxes) — and, for
    ``nsteps=2``, the mid-pair state itself — lives in VMEM only: per
    kernel call the state is read and written once (plus an
    ``8 * nsteps``-row margin per ``_PBLK``-row block), instead of
    materializing ~10 intermediate full fields through HBM per step.
    Single-rank periodic-x decompositions only (the benchmark
    configuration); multi-rank meshes use ``model_step_pallas_halo``, which
    keeps the same kernels but splices real exchanges between the phases.
    Equality with
    the jnp step is pinned by
    tests/test_examples.py::test_pallas_step_matches_fast_step and
    ::test_pallas_pair_step_matches_fast_steps (interpret mode on CPU,
    compiled on TPU).

    ``interpret=None`` resolves at trace time to "the comm's mesh is not
    on TPU devices", so the same call sites run the Mosaic-compiled kernel
    on the chip and the interpret path everywhere else (CPU CI, the
    driver's compile check).
    """
    if not (cfg.nproc == 1 and cfg.periodic_x):
        raise ValueError(
            "model_step_pallas: single-rank periodic-x only; use "
            "model_step_fast"
        )
    # one sublane tile of validity per fused step, rounded up to a divisor
    # of _PBLK — the prev/next margin index maps address mrg-row blocks as
    # i * (_PBLK // mrg), which only lands on block starts when mrg
    # divides _PBLK (nsteps=3: 24 -> 32); nsteps=4 exceeds the chip's
    # VMEM/compiler limits at benchmark width (checked in _margin_rows)
    mrg = _margin_rows(nsteps)
    import jax.experimental.pallas as pl

    if interpret is None:
        interpret = _resolve_interpret(comm)

    ny, nx = cfg.ny_local, cfg.nx_local
    fields = state
    # inside shard_map with VMA checking the outputs must be typed as
    # varying over the mesh axes, like the (sharded) inputs
    vma = frozenset(getattr(jax.typeof(state.h), "vma", frozenset()))
    if interpret and vma:
        # interpret mode inlines the kernel jaxpr under shard_map's
        # varying-manual-axes checking, where kernel-created iotas and
        # literals (unvarying) cannot mix with varying operands.  The
        # kernel only ever runs on a 1x1 mesh (nproc == 1), so the axes
        # are size-1 and a psum is an exact identity that makes every
        # operand axis-invariant; the outputs are re-varied below.
        axes = tuple(vma)
        fields = State(*(jax.lax.psum(f, axes) for f in state))
        out_vma = frozenset()
    else:
        out_vma = vma
    h, u, v, dh, du, dv = fields

    grid, main_spec, prev_spec, next_spec = _blocked_specs(ny, nx, mrg)

    in_specs = []
    operands = []
    for f in (h, u, v, dh, du, dv):
        in_specs += [prev_spec, main_spec, next_spec]
        operands += [f, f, f]

    out_shape = [
        jax.ShapeDtypeStruct((ny, nx), jnp.float32, vma=out_vma)
    ] * 6
    outs = pl.pallas_call(
        lambda *refs: _sw_steps_kernel(cfg, first_step, ny, mrg, nsteps, refs),
        grid=grid,
        in_specs=in_specs,
        out_specs=[main_spec for _ in range(6)],
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=None if interpret else _tpu_compiler_params(),
    )(*operands)
    if interpret and vma:
        outs = [jax.lax.pcast(o, axes, to="varying") for o in outs]
    h1, u1, v1, dh_new, du_new, dv_new = outs

    # end-of-step exchanges: none — on this (single-rank, periodic-x) path
    # they reduce to the periodic column fix, which the kernel applies
    # in-register before storing, saving three full-field HBM round-trips
    return State(h1, u1, v1, dh_new, du_new, dv_new)


def model_step2_pallas(state: State, cfg: Config, comm: mpx.Comm,
                       first_step: bool, interpret=None) -> State:
    """TWO model steps in one Pallas kernel call (``model_step_pallas``
    with ``nsteps=2``): halves the per-step HBM traffic and the grid
    dispatch count.  Amortized (dispatch-constant-cancelled) measurement:
    992 -> 870 µs/step over the single-step kernel."""
    return model_step_pallas(state, cfg, comm, first_step,
                             interpret=interpret, nsteps=2)


def model_step3_pallas(state: State, cfg: Config, comm: mpx.Comm,
                       first_step: bool, interpret=None) -> State:
    """THREE model steps per kernel call.  NOT the shipped depth: the
    margin must divide ``_PBLK`` so three steps need 32 margin rows (not
    24), and the measured margin-recompute overhead (192-row windows per
    128 stored rows) outweighs the HBM saving — narrower blocks
    (96 + 2·24) measured 859 µs/step vs the pair kernel's 870, within
    noise, and the 192-row window fails to compile at benchmark width.
    Kept as an explicit mode because the depth generalization is tested
    and useful at smaller widths; ``"auto"`` ships the pair."""
    return model_step_pallas(state, cfg, comm, first_step,
                             interpret=interpret, nsteps=3)


# ---------------------------------------------------------------------------
# Pallas split-phase step (any mesh: kernel compute + real halo exchanges)
# ---------------------------------------------------------------------------


def _rank_offsets(cfg: Config):
    """This rank's domain-global (row, col) offset as a ``(2,)`` int32
    vector — the SMEM scalar operand that lets ONE compiled kernel serve
    every rank position (all wall masks test ``local index + offset``)."""
    row = jax.lax.axis_index("py") * (cfg.ny_local - 2)
    col = jax.lax.axis_index("px") * (cfg.nx_local - 2)
    return jnp.stack([row.astype(jnp.int32), col.astype(jnp.int32)])


def _sw_phase_kernel(cfg: Config, mrg: int, nfields: int, window, refs):
    """Kernel body shared by the two phase kernels: assemble ``nfields``
    row windows from [prev-margin, main, next-margin] block triples, label
    them with local + global indices (rank offsets from the leading SMEM
    operand), apply ``window``, store the main rows."""
    import jax.experimental.pallas as pl

    meta = refs[0]
    ins, outs = refs[1:1 + 3 * nfields], refs[1 + 3 * nfields:]
    nx = cfg.nx_local
    nr = _PBLK + 2 * mrg
    fields = _window_fields(ins, nfields)

    pid = pl.program_id(0)
    iy = jax.lax.broadcasted_iota(jnp.int32, (nr, nx), 0) + pid * _PBLK - mrg
    ix = jax.lax.broadcasted_iota(jnp.int32, (nr, nx), 1)
    giy = iy + meta[0]
    gix = ix + meta[1]

    out_fields = window(iy, ix, giy, gix, fields)
    sl = slice(mrg, mrg + _PBLK)
    for o, f in zip(outs, out_fields):
        o[:] = f[sl]


def _phase_pallas_call(cfg: Config, window, meta, fields, n_out: int,
                       out_vma):
    """Run ``window`` (a ``_phase*_window`` closure) as a compiled blocked
    Pallas kernel over the rank-local arrays in ``fields``."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    mrg = 8  # one sublane tile covers both phases' recompute chain depths
    ny, nx = cfg.ny_local, cfg.nx_local
    grid, main_spec, prev_spec, next_spec = _blocked_specs(ny, nx, mrg)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    operands = [meta]
    for f in fields:
        in_specs += [prev_spec, main_spec, next_spec]
        operands += [f, f, f]

    out_shape = [
        jax.ShapeDtypeStruct((ny, nx), jnp.float32, vma=out_vma)
    ] * n_out
    return pl.pallas_call(
        lambda *refs: _sw_phase_kernel(cfg, mrg, len(fields), window, refs),
        grid=grid,
        in_specs=in_specs,
        out_specs=[main_spec for _ in range(n_out)],
        out_shape=out_shape,
        compiler_params=_tpu_compiler_params(),
    )(*operands)


def model_step_pallas_halo(state: State, cfg: Config, comm: mpx.Comm,
                           first_step: bool, interpret=None) -> State:
    """One model step on ANY mesh decomposition: fused Pallas compute with
    real ``sendrecv`` halo exchanges spliced between the phases.

    Where the whole-step kernel (``model_step_pallas``) folds every halo
    refresh into an in-register periodic column fix — possible only when
    one rank owns the whole domain — this path keeps ``model_step_fast``'s
    exchange structure (integrate → exchange h,u,v → viscosity → exchange
    u,v; see its docstring for why the derived fields need no exchange at
    all) and replaces the two *compute* regions with blocked Pallas
    kernels: ``_phase1_window`` (hc, fluxes, q, ke, tendencies, AB update
    — every intermediate stays in VMEM) and ``_phase2_window`` (viscous
    fluxes).  Per step the state round-trips HBM twice (once per phase)
    instead of once (whole-step kernel) but far under the jnp path's ~10
    intermediate full fields.  Rank position enters the compiled kernel as
    an SMEM scalar pair (``_rank_offsets``), so one kernel serves all
    ranks of the SPMD program.

    On non-TPU backends (``interpret`` resolves true) the same window
    functions are evaluated directly on the full local array with
    ``jnp.roll`` — identical arithmetic, no Pallas machinery — because
    Mosaic cannot compile there and Pallas interpret mode cannot inline
    kernel jaxprs under shard_map's varying-axes checking on a real
    multi-rank mesh (the single-rank psum identity used by
    ``model_step_pallas`` has no multi-rank analog).  Equality with
    ``model_step_fast`` on a (2, 4) mesh is pinned in
    tests/test_examples.py; the compiled kernels are exercised on-chip by
    the (1, 1)-mesh TPU path, which shares every line of kernel code.
    """
    if interpret is None:
        interpret = _resolve_interpret(comm)

    token = mpx.create_token()
    meta = _rank_offsets(cfg)
    nyl, nxl = cfg.ny_local, cfg.nx_local
    vma = frozenset(getattr(jax.typeof(state.h), "vma", frozenset()))

    if interpret:
        iy = jax.lax.broadcasted_iota(jnp.int32, (nyl, nxl), 0)
        ix = jax.lax.broadcasted_iota(jnp.int32, (nyl, nxl), 1)
        giy, gix = iy + meta[0], ix + meta[1]
        outs = _phase1_window(
            cfg, first_step, iy, ix, giy, gix, tuple(state), jnp.roll
        )
    else:
        outs = _phase_pallas_call(
            cfg,
            lambda iy, ix, giy, gix, fs: _phase1_window(
                cfg, first_step, iy, ix, giy, gix, fs, _pltpu_roll()
            ),
            meta, tuple(state), 6, vma,
        )
    h1, u1, v1, dh_new, du_new, dv_new = outs

    h1, token = enforce_boundaries(h1, "h", cfg, comm, token)
    u1, token = enforce_boundaries(u1, "u", cfg, comm, token)
    v1, token = enforce_boundaries(v1, "v", cfg, comm, token)

    if cfg.lateral_viscosity > 0:
        if interpret:
            u1, v1 = _phase2_window(cfg, iy, ix, giy, gix, u1, v1, jnp.roll)
        else:
            u1, v1 = _phase_pallas_call(
                cfg,
                lambda iy, ix, giy, gix, fs: _phase2_window(
                    cfg, iy, ix, giy, gix, fs[0], fs[1], _pltpu_roll()
                ),
                meta, (u1, v1), 2, vma,
            )
        # restore the coherent-halo invariant for the next step (pure halo
        # refresh, kind "h" — see model_step_fast)
        u1, token = enforce_boundaries(u1, "h", cfg, comm, token)
        v1, token = enforce_boundaries(v1, "h", cfg, comm, token)

    return State(h1, u1, v1, dh_new, du_new, dv_new)


# ---------------------------------------------------------------------------
# Pallas wide-halo step (any mesh: communication-avoiding fused kernel)
# ---------------------------------------------------------------------------


def _strip_exch(payload, route, c, token):
    """Exchange one batched halo strip along a direction: a single
    ``sendrecv``, with a zeros recv template (``MPI_PROC_NULL``: edge
    ranks of non-wrapping directions keep zeros).  Size-1 axes resolve
    without any collective — identity for a wrapping route, zeros for a
    non-wrapping one.  Every strip exchange gets the CALLER's token, not
    a chain: the exchanges of one widening/refresh are mutually
    independent (the x -> y phase ordering is a data dependency already),
    and chaining would serialize what XLA can overlap."""
    if c.Get_size() == 1:
        return payload if route.wrap else jnp.zeros_like(payload)
    out, _ = mpx.sendrecv(payload, jnp.zeros_like(payload), dest=route,
                          comm=c, token=token)
    return out


def _wide_exchange(fields, cfg: Config, comm: mpx.Comm, m: int, token):
    """Build the widened frame for ``model_step_pallas_wide``: every side
    gains ``m - 1`` rows/cols of neighbor data beyond the existing 1-cell
    halo, so ``nsteps`` whole model steps can be recomputed locally with no
    further exchange (a communication-avoiding halo exchange).

    Exchanges ``m``-deep strips of all six fields, batched as ONE
    ``sendrecv`` per direction — 4 messages per multi-step kernel call,
    where the split-phase path sends 4 messages per ``enforce_boundaries``
    round and needs 5 rounds per step.  Corner (diagonal-neighbor) data
    arrives via the standard two-phase trick: x strips first, then y
    strips *of the x-widened arrays*.

    Assembly differs by field class, preserving each class's invariant:

    - state (``h``/``u``/``v``): the local array is kept whole — its halo
      ring already holds the correct value everywhere (coherent at seams;
      the *initial-condition* value at physical walls, which an exchanged
      strip could not supply) — and the strips contribute only the
      ``m - 1`` extra rows/cols beyond it;
    - tendencies (``dh``/``du``/``dv``): their local halo ring is zero by
      invariant, but in the widened frame the seam position must hold the
      *owning* rank's value (the AB-2 update reads it there), so the full
      ``m``-deep strip replaces the halo position; at walls the zeros
      template reproduces the invariant exactly.

    Edge ranks of non-wrapping directions get a zeros template
    (``MPI_PROC_NULL`` semantics); those cells are beyond-wall garbage
    that the wide masks keep out of every valid cell.
    """
    nyl, nxl = cfg.ny_local, cfg.nx_local
    commx, commy = comm.sub("px"), comm.sub("py")
    wrap_x = cfg.periodic_x

    # ---- x phase: (6, nyl, m) strips --------------------------------
    lo = jnp.stack([f[:, 1:m + 1] for f in fields])
    hi = jnp.stack([f[:, nxl - 1 - m:nxl - 1] for f in fields])
    # high-side strips travel east (shift +1): each rank receives its WEST
    # neighbor's easternmost interior columns, and vice versa
    from_west = _strip_exch(hi, shift(+1, wrap=wrap_x), commx, token)
    from_east = _strip_exch(lo, shift(-1, wrap=wrap_x), commx, token)
    wx = []
    for k, f in enumerate(fields):
        w, e = from_west[k], from_east[k]
        if k < 3:  # state: local halo ring kept in place
            wx.append(jnp.concatenate([w[:, :m - 1], f, e[:, 1:]], axis=1))
        else:  # tendency: the strip supplies the halo position
            wx.append(jnp.concatenate([w, f[:, 1:-1], e], axis=1))

    # ---- y phase: (6, m, nx_w) strips of the x-widened arrays -------
    lo = jnp.stack([f[1:m + 1] for f in wx])
    hi = jnp.stack([f[nyl - 1 - m:nyl - 1] for f in wx])
    from_south = _strip_exch(hi, shift(+1, wrap=False), commy, token)
    from_north = _strip_exch(lo, shift(-1, wrap=False), commy, token)
    out = []
    for k, f in enumerate(wx):
        s, n = from_south[k], from_north[k]
        if k < 3:
            out.append(jnp.concatenate([s[:m - 1], f, n[1:]], axis=0))
        else:
            out.append(jnp.concatenate([s, f[1:-1], n], axis=0))
    return tuple(out), token


def _wide_step_window(cfg: Config, first_step: bool, giy, gix, fields, roll):
    """One WHOLE model step on the widened frame: ``_phase1_window`` with
    the wide masks, the post-integration wall conditions as global-index
    ``where``s (the only thing the mid-step exchange does *beyond* halo
    refresh — which the wide frame gets by recompute), then
    ``_phase2_window``.  No exchanges and no periodic fixes: x wrap data
    is real far-side data sitting in the widened margins.  Validity
    shrinks by the recompute chain depth (~5 cells) per step from the
    widened edges inward."""
    gy_n, gx_n = cfg.ny + 2, cfg.nx + 2
    h1, u1, v1, dh_n, du_n, dv_n = _phase1_window(
        cfg, first_step, giy, gix, giy, gix, fields, roll, wide=True
    )
    # post-integration wall conditions (enforce_boundaries kinds "u"/"v";
    # global-index masks, so a rank whose widened frame reaches a wall row
    # applies the same zeroing the wall rank applies)
    if not cfg.periodic_x:
        u1 = jnp.where(gix == gx_n - 2, 0.0, u1)
    v1 = jnp.where(giy == gy_n - 2, 0.0, v1)
    if cfg.lateral_viscosity > 0:
        u1, v1 = _phase2_window(
            cfg, giy, gix, giy, gix, u1, v1, roll, wide=True
        )
    # end-of-step kind-"h" refreshes are pure halo refresh: nothing to do
    return h1, u1, v1, dh_n, du_n, dv_n


def _sw_wide_kernel(cfg: Config, first_step: bool, mrg: int, nsteps: int,
                    refs):
    """Kernel body for the wide-halo step: like ``_sw_steps_kernel`` but on
    the widened frame — global indices come from the SMEM offset pair (one
    compiled kernel serves every rank) and the step windows use the wide
    masks, so there are no periodic fixes."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    meta = refs[0]
    ins, outs = refs[1:19], refs[19:]
    nx_w = ins[1].shape[1]
    nr = _PBLK + 2 * mrg
    fields = _window_fields(ins, 6)

    pid = pl.program_id(0)
    wy = (
        jax.lax.broadcasted_iota(jnp.int32, (nr, nx_w), 0)
        + pid * _PBLK
        - mrg
    )
    wx = jax.lax.broadcasted_iota(jnp.int32, (nr, nx_w), 1)
    giy = wy + meta[0]
    gix = wx + meta[1]

    first = first_step
    for _ in range(nsteps):
        fields = _wide_step_window(cfg, first, giy, gix, fields, pltpu.roll)
        first = False

    sl = slice(mrg, mrg + _PBLK)
    for o, f in zip(outs, fields):
        o[:] = f[sl]


def model_step_pallas_wide(state: State, cfg: Config, comm: mpx.Comm,
                           first_step: bool, interpret=None,
                           nsteps: int = 2) -> State:
    """``nsteps`` whole model steps on ANY mesh as ONE fused Pallas kernel
    between communication-avoiding wide halo exchanges.

    Where ``model_step_pallas_halo`` splices a real 1-cell exchange
    between the two phase kernels of every step (5 exchange rounds and two
    state HBM round-trips per step), this path exchanges ``8 * nsteps``
    -deep strips of all six fields ONCE (4 batched messages), then runs
    the whole multi-step chain in VMEM: every halo value a step would have
    received is instead *recomputed locally* from the widened margins —
    bit-identical to the exchange, because the seam cell is computed by
    the identical expression tree on the identical operand values its
    owning rank uses (``_window_masks(wide=True)``).  The cropped result
    therefore equals ``model_step_fast`` exactly, which
    tests/test_examples.py pins on (1,1) and (2,4) meshes in both
    boundary modes.

    This brings the single-rank pair kernel's economics (state reads HBM
    once per ``nsteps``, all intermediates in VMEM) to multi-rank meshes:
    the reference's scaling story (ref docs/shallow-water.rst:56-94) with
    the fused-kernel per-chip speed.  Requires a local interior of at
    least ``8 * nsteps`` cells per dimension (strips must come from the
    immediate neighbor only); ``select_steps("auto")`` falls back to the
    split-phase path below that.
    """
    m = _margin_rows(nsteps)
    if cfg.ny_local - 2 < m or cfg.nx_local - 2 < m:
        # ValueError, not assert: user-facing eligibility that must
        # survive `python -O` (an undersized interior would silently
        # exchange out-of-range strips)
        raise ValueError(
            "model_step_pallas_wide: local interior must be >= the exchange "
            f"depth ({m}) in both dimensions; use model_step_pallas_halo"
        )
    if interpret is None:
        interpret = _resolve_interpret(comm)
    token = mpx.create_token()
    wfields, token = _wide_exchange(tuple(state), cfg, comm, m, token)
    outs = _wide_kernel_call(wfields, cfg, first_step, nsteps, m, interpret)
    return _wide_crop(outs, cfg, m)


def _wide_kernel_call(wfields, cfg: Config, first_step: bool, nsteps: int,
                      m: int, interpret: bool):
    """``nsteps`` step windows on the widened frame: the compiled blocked
    Pallas kernel, or direct ``jnp.roll`` evaluation where Mosaic cannot
    compile (same rationale as ``model_step_pallas_halo``)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ny_w, nx_w = wfields[0].shape
    off = _rank_offsets(cfg) - (m - 1)  # widened-frame global offsets
    vma = frozenset(getattr(jax.typeof(wfields[0]), "vma", frozenset()))

    if interpret:
        iy = jax.lax.broadcasted_iota(jnp.int32, (ny_w, nx_w), 0)
        ix = jax.lax.broadcasted_iota(jnp.int32, (ny_w, nx_w), 1)
        giy, gix = iy + off[0], ix + off[1]
        outs = tuple(wfields)
        first = first_step
        for _ in range(nsteps):
            outs = _wide_step_window(cfg, first, giy, gix, outs, jnp.roll)
            first = False
        return outs

    grid, main_spec, prev_spec, next_spec = _blocked_specs(ny_w, nx_w, m)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    operands = [off]
    for f in wfields:
        in_specs += [prev_spec, main_spec, next_spec]
        operands += [f, f, f]
    out_shape = [
        jax.ShapeDtypeStruct((ny_w, nx_w), jnp.float32, vma=vma)
    ] * 6
    return pl.pallas_call(
        lambda *refs: _sw_wide_kernel(cfg, first_step, m, nsteps, refs),
        grid=grid,
        in_specs=in_specs,
        out_specs=[main_spec for _ in range(6)],
        out_shape=out_shape,
        compiler_params=_tpu_compiler_params(),
    )(*operands)


def _wide_crop(outs, cfg: Config, m: int) -> State:
    """Crop a widened frame back to the local layout: the state's halo
    ring lands coherent (seam cells were updated exactly as their owner
    updates them; wall halo cells kept their values — update masked,
    tendency position zero), and the tendency ring is re-zeroed (in the
    widened frame it holds the neighbor's values at seams)."""
    nyl, nxl = cfg.ny_local, cfg.nx_local
    sl = (slice(m - 1, m - 1 + nyl), slice(m - 1, m - 1 + nxl))
    h1, u1, v1 = (o[sl] for o in outs[:3])
    liy = jax.lax.broadcasted_iota(jnp.int32, (nyl, nxl), 0)
    lix = jax.lax.broadcasted_iota(jnp.int32, (nyl, nxl), 1)
    ring = (liy == 0) | (liy == nyl - 1) | (lix == 0) | (lix == nxl - 1)
    dh_n, du_n, dv_n = (jnp.where(ring, 0.0, o[sl]) for o in outs[3:])
    return State(h1, u1, v1, dh_n, du_n, dv_n)


def _wide_refresh(wf, cfg: Config, comm: mpx.Comm, m: int, token):
    """Refresh the margin bands of a CARRIED widened frame between
    multi-step kernel calls.

    After a kernel call the local frame (crop region, halo ring included)
    is valid but the ``m - 1``-deep margins are recompute garbage.  The
    carried-frame driver (``solve_fused`` wide modes) therefore never
    crops between calls: it exchanges just the margin bands — four
    messages of ``(6, ·, m-1)`` — and updates them in place with
    ``.at[].set`` (inside the ``fori_loop`` XLA updates the carried
    buffers without copying the untouched interior), so the full-array
    concat/crop copies of ``model_step_pallas_wide`` happen once per RUN
    instead of once per pair of steps.

    Two-phase for corners: x bands first (their corner rows are the
    sender's own garbage y-margins), then y bands at full widened width —
    sliced *after* the x update, so their corner columns carry the
    y-neighbor's freshly refreshed x margins (= diagonal-neighbor data).
    In the carried frame the state/tendency assembly distinction of
    ``_wide_exchange`` disappears: the halo-position ring is valid
    post-kernel (computed as the owner computes it) and is not touched.
    """
    e = m - 1
    nyl, nxl = cfg.ny_local, cfg.nx_local
    ny_w, nx_w = wf[0].shape
    commx, commy = comm.sub("px"), comm.sub("py")
    wrap_x = cfg.periodic_x

    # ---- x bands: (6, ny_w, e) ----
    # west margin <- west neighbor's easternmost interior (its widened
    # cols [nxl-2, nxl-2+e)); east margin <- east neighbor's westernmost
    # (its widened cols [e+2, 2e+2))
    from_west = _strip_exch(
        jnp.stack([f[:, nxl - 2:nxl - 2 + e] for f in wf]),
        shift(+1, wrap=wrap_x), commx, token,
    )
    from_east = _strip_exch(
        jnp.stack([f[:, e + 2:2 * e + 2] for f in wf]),
        shift(-1, wrap=wrap_x), commx, token,
    )
    wf = tuple(
        f.at[:, :e].set(from_west[k]).at[:, e + nxl:].set(from_east[k])
        for k, f in enumerate(wf)
    )

    # ---- y bands: (6, e, nx_w), full width (corners now valid) ----
    from_south = _strip_exch(
        jnp.stack([f[nyl - 2:nyl - 2 + e] for f in wf]),
        shift(+1, wrap=False), commy, token,
    )
    from_north = _strip_exch(
        jnp.stack([f[e + 2:2 * e + 2] for f in wf]),
        shift(-1, wrap=False), commy, token,
    )
    return tuple(
        f.at[:e, :].set(from_south[k]).at[e + nyl:, :].set(from_north[k])
        for k, f in enumerate(wf)
    )


def _wide_run(state: State, num_steps: int, cfg: Config, comm: mpx.Comm,
              chunk_size: int, m: int, interpret: bool,
              euler_first: bool) -> State:
    """Advance ``num_steps`` model steps on the CARRIED widened frame:
    build the frame once (``_wide_exchange``), run ``chunk_size``-step
    kernel calls with only a margin-band refresh between them
    (``_wide_refresh``), crop once at the end.  ``euler_first`` makes the
    first advanced step the forward-Euler one (a 1-step kernel call).
    This is the hot path behind every wide-mode driver (``make_stepper``
    and ``solve_fused``)."""
    if cfg.ny_local - 2 < m or cfg.nx_local - 2 < m:
        raise ValueError(
            "wide-halo path: local interior must be >= the exchange depth "
            f"({m}) in both dimensions; use model_step_pallas_halo"
        )
    if num_steps <= 0:
        return state
    token = mpx.create_token()
    wf, token = _wide_exchange(tuple(state), cfg, comm, m, token)
    rest = num_steps
    # `fresh` tracks whether the margins are still the just-exchanged ones
    # (a kernel call invalidates them); the first call after the build can
    # then skip its redundant refresh
    fresh = True
    if euler_first:
        wf = _wide_kernel_call(wf, cfg, True, 1, m, interpret)
        rest -= 1
        fresh = False
    nchunks, rem = divmod(rest, chunk_size)

    def body(_, wf):
        wf = _wide_refresh(wf, cfg, comm, m, token)
        return _wide_kernel_call(wf, cfg, False, chunk_size, m, interpret)

    if nchunks and fresh:
        wf = _wide_kernel_call(wf, cfg, False, chunk_size, m, interpret)
        nchunks -= 1
        fresh = False
    if nchunks:  # fori_loop(0, 0) would still trace the chunk kernel
        wf = jax.lax.fori_loop(0, nchunks, body, tuple(wf))
    for _ in range(rem):
        if fresh:
            fresh = False
        else:
            wf = _wide_refresh(wf, cfg, comm, m, token)
        wf = _wide_kernel_call(wf, cfg, False, 1, m, interpret)
    return _wide_crop(wf, cfg, m)


def model_step_wide(state: State, cfg: Config, comm: mpx.Comm,
                    first_step: bool, interpret=None) -> State:
    """One model step via the wide-halo kernel (``nsteps=1``)."""
    return model_step_pallas_wide(state, cfg, comm, first_step,
                                  interpret=interpret, nsteps=1)


def model_step2_wide(state: State, cfg: Config, comm: mpx.Comm,
                     first_step: bool, interpret=None) -> State:
    """TWO model steps per wide-halo kernel call + exchange round."""
    return model_step_pallas_wide(state, cfg, comm, first_step,
                                  interpret=interpret, nsteps=2)


def _pltpu_roll():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.roll


def select_step(fast, cfg: Config = None):
    """The model-step implementation behind ``fast``: the single source of
    truth for every driver (make_stepper, solve_fused, bench.py).

    ``fast`` is one of:

    - ``False`` — the reference-structured step (parity oracle);
    - ``True`` — ``model_step_fast`` (works on any mesh);
    - ``"pallas"`` / ``"pallas2"`` / ``"pallas3"`` — the fused whole-step
      Pallas kernel (single-rank periodic-x only; raises otherwise);
      ``"pallas2"``/``"pallas3"`` additionally fuse 2/3 steps per kernel
      call (see ``select_steps``);
    - ``"pallas_halo"`` — the split-phase Pallas kernels with real halo
      exchanges between them (any mesh, ``model_step_pallas_halo``);
    - ``"wide"`` / ``"wide2"`` — the communication-avoiding wide-halo
      kernel (any mesh with local interior >= 8/16 cells per dimension,
      ``model_step_pallas_wide``); ``"wide2"`` fuses 2 steps per exchange;
    - ``"auto"`` — ``"pallas2"`` when ``cfg`` is a single-rank periodic-x
      decomposition (the benchmark configuration); else ``"wide2"`` when
      the local interior fits its exchange depth; else ``"pallas_halo"``.

    Returns the SINGLE-step callable; drivers that can batch steps use
    ``select_steps`` to also obtain the multi-step chunk kernel.
    """
    return select_steps(fast, cfg)[0]


def select_steps(fast, cfg: Config = None):
    """``(single_step, chunk_step_or_None, chunk_size)`` behind ``fast``
    (see ``select_step`` for the mode table).  ``chunk_step`` advances
    ``chunk_size`` model steps per call and is only offered for the fused
    Pallas chunk modes; callers use it for whole chunks and fall back to
    ``single_step`` for the first (Euler) step and remainders."""
    if fast == "auto":
        if cfg is None:
            raise ValueError(
                "select_step('auto') needs the Config to decide kernel "
                "eligibility — pass cfg"
            )
        # whole-step kernel where eligible (no exchanges at all); the
        # wide-halo pair kernel everywhere else (multi-rank meshes, walls)
        # unless the local interior is smaller than its exchange depth.
        # Pair depth: deeper fusion measured no better (see
        # model_step3_pallas) and fails to compile at benchmark width.
        if cfg.nproc == 1 and cfg.periodic_x:
            fast = "pallas2"
        elif min(cfg.ny_local, cfg.nx_local) - 2 >= _margin_rows(2):
            fast = "wide2"
        else:
            fast = "pallas_halo"
    if fast == "wide2":
        return model_step_wide, model_step2_wide, 2
    if fast == "wide":
        return model_step_wide, None, 1
    if fast == "pallas3":
        return model_step_pallas, model_step3_pallas, 3
    if fast == "pallas2":
        return model_step_pallas, model_step2_pallas, 2
    if fast == "pallas":
        return model_step_pallas, None, 1
    if fast == "pallas_halo":
        return model_step_pallas_halo, None, 1
    return (model_step_fast if fast else model_step), None, 1


def make_stepper(cfg: Config, comm: mpx.Comm, *, fast=True):
    """Compile the two region programs: the first (Euler) step and an
    n-step AB-2 multistep (``lax.fori_loop`` inside the region — one XLA
    program per multistep, ref examples/shallow_water.py:415-420).

    ``fast`` selects the TPU-restructured step (``model_step_fast``,
    default); ``fast=False`` keeps the reference-structured step;
    ``"pallas"``/``"pallas2"``/``"pallas3"``/``"auto"`` select the fused
    whole-step kernel (see ``select_steps``) — all verified equal in
    tests/test_examples.py.  ``multistep`` advances exactly ``num_steps``
    steps in every mode (the chunk kernel handles whole chunks; the
    remainder falls back to single-step calls).
    """
    step, chunk, chunk_size = select_steps(fast, cfg)

    if step is model_step_wide:
        # wide modes run on the carried widened frame (margin-band refresh
        # between kernel calls instead of crop + re-widen per call)
        m = _margin_rows(chunk_size)
        interpret = _resolve_interpret(comm)

        @partial(mpx.spmd, comm=comm)
        def first_step(state: State) -> State:
            return _wide_run(state, 1, cfg, comm, chunk_size, m, interpret,
                             euler_first=True)

        @partial(mpx.spmd, comm=comm, static_argnums=(1,))
        def multistep(state: State, num_steps: int) -> State:
            return _wide_run(state, num_steps, cfg, comm, chunk_size, m,
                             interpret, euler_first=False)

        return first_step, multistep

    @partial(mpx.spmd, comm=comm)
    def first_step(state: State) -> State:
        return step(state, cfg, comm, first_step=True)

    @partial(mpx.spmd, comm=comm, static_argnums=(1,))
    def multistep(state: State, num_steps: int) -> State:
        state = _run_steps(state, num_steps, cfg, comm, step, chunk,
                           chunk_size)
        return state

    return first_step, multistep


def _run_steps(state: State, num_steps: int, cfg, comm, step, chunk,
               chunk_size: int) -> State:
    """Advance ``num_steps`` non-first steps, using the chunk kernel for
    whole ``chunk_size``-step runs when available (``num_steps`` is
    static; the remainder is at most ``chunk_size - 1`` single steps)."""
    if chunk is not None:
        nchunks, rem = divmod(num_steps, chunk_size)
        if nchunks:  # fori_loop(0, 0) would still trace the chunk kernel
            state = jax.lax.fori_loop(
                0, nchunks, lambda _, s: chunk(s, cfg, comm, False), state
            )
        for _ in range(rem):
            state = step(state, cfg, comm, False)
        return state
    return jax.lax.fori_loop(
        0, num_steps, lambda _, s: step(s, cfg, comm, False), state
    )


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def solve(cfg: Config, t1: float, *, num_multisteps: int = 10, devices=None,
          collect: bool = True, verbose: bool = False, fast=True):
    """Iterate the model to time ``t1`` [s].  Returns ``(snapshots,
    wall_time_s, n_steps)``; ``snapshots`` is a list of stacked-block h
    fields (empty when ``collect=False``)."""
    mesh, comm = make_mesh_and_comm(cfg, devices=devices)
    first_step, multistep = make_stepper(cfg, comm, fast=fast)

    state = initial_state(cfg)
    snapshots = [np.asarray(state.h)] if collect else []

    state = first_step(state)
    if collect:
        snapshots.append(np.asarray(state.h))
    t = cfg.dt

    # warm-up compile (excluded from timing, like the reference's
    # pre-compilation at examples/shallow_water.py:449-450); the host fetch
    # drains the async dispatch queue — block_until_ready alone is not a
    # reliable sync point on remote-attached devices
    np.asarray(multistep(state, num_multisteps).h[0, 0, 0])

    n_steps = 1
    start = time.perf_counter()
    while t < t1:
        state = multistep(state, num_multisteps)
        if collect:
            snapshots.append(np.asarray(state.h))  # device->host sync
        t += cfg.dt * num_multisteps
        n_steps += num_multisteps
        if verbose:
            print(f"  t = {t / DAY_IN_SECONDS:.3f} days", end="\r")
    if not collect:
        # pipelined throughput mode: one sync at the end (single-element
        # fetch: full-array fetches are seconds-slow on tunneled devices)
        np.asarray(state.h[0, 0, 0])
    wall = time.perf_counter() - start

    # collect the full solution at rank 0 — exercises the eager gather path
    # (ref examples/shallow_water.py:588 uses mpi4jax.gather the same way);
    # appended as an extra snapshot, so the last two entries hold the same
    # final state (stacked view, then root-gathered view)
    if collect:
        gathered, _ = mpx.gather(state.h, root=0, comm=comm)
        snapshots.append(np.asarray(gathered[0]))

    return snapshots, wall, n_steps


def solve_fused(cfg: Config, t1: float, *, num_multisteps: int = 10,
                devices=None, fast=True, return_state=False,
                pinned: bool = False, unroll: int = 0, info: dict = None):
    """Benchmark-mode solve: the ENTIRE simulation is one XLA program
    (first Euler step + a ``fori_loop`` over all remaining steps), so the
    host dispatches once instead of once per multistep.  Runs the same
    number of steps as ``solve(collect=False)``; returns
    ``(wall_time_s, n_steps)`` with compile excluded (reference protocol,
    ref examples/shallow_water.py:449-450), plus the final stacked state
    when ``return_state`` is set (equality tests).

    The wide-halo modes get a dedicated fused program that carries the
    state in WIDENED form across the whole run: the widened frame is built
    once, each pair of steps exchanges only the thin margin bands
    (``_wide_refresh``) before its kernel call, and the crop back to the
    local layout happens once at the end — per pair this costs four
    band messages and zero full-array copies, where cropping and
    re-widening every call costs two extra full-state HBM round-trips.

    ``unroll=N`` (> 0) switches to megastep mode: the run becomes
    ``ceil((n_steps - 1)/N)`` pinned megastep dispatches of N
    device-resident steps each (``mpx.compile(..., unroll=N)``,
    docs/aot.md "Megastep execution") — unroll implies pinning.  When
    ``info`` (a dict) is passed, ``info["unroll"]`` records the trip
    count that ACTUALLY executed (0 on fallback), so callers like
    bench.py stamp only configurations that ran.
    """
    mesh, comm = make_mesh_and_comm(cfg, devices=devices)
    n_iters = max(0, math.ceil((t1 - cfg.dt) / (cfg.dt * num_multisteps)))
    n_steps = 1 + n_iters * num_multisteps
    step, chunk, chunk_size = select_steps(fast, cfg)

    if step is model_step_wide:
        m = _margin_rows(chunk_size)
        interpret = _resolve_interpret(comm)

        @partial(mpx.spmd, comm=comm, static_argnums=(1,))
        def fused(state: State, total: int) -> State:
            return _wide_run(state, total + 1, cfg, comm, chunk_size, m,
                             interpret, euler_first=True)

    else:
        @partial(mpx.spmd, comm=comm, static_argnums=(1,))
        def fused(state: State, total: int) -> State:
            state = step(state, cfg, comm, first_step=True)
            return _run_steps(state, total, cfg, comm, step, chunk,
                              chunk_size)

    state = initial_state(cfg)
    runner = fused
    if info is not None:
        # what actually executed: the megastep block below flips
        # "unroll" on success only, so a fallback run never stamps a
        # megastep configuration it did not use (mirrors the aot-stats
        # guard bench.py applies to "pinned")
        info["unroll"] = 0
    megastep_ok = False
    if unroll and unroll > 0:
        # Megastep mode (docs/aot.md "Megastep execution"): instead of
        # one whole-run program, the run is ceil((n_steps - 1)/unroll)
        # pinned megastep dispatches of `unroll` device-resident steps
        # each — the configuration that exposes per-dispatch host cost
        # so bench.py --unroll can show it amortizing as 1/N.  The Euler
        # first step runs through the whole-run program at total=0.
        def one_step(state: State) -> State:
            if step is model_step_wide:
                return _wide_run(state, 1, cfg, comm, chunk_size, m,
                                 interpret, euler_first=False)
            return _run_steps(state, 1, cfg, comm, step, chunk, chunk_size)

        try:
            n_mega, tail = divmod(n_steps - 1, unroll)
            pp = (mpx.compile(one_step, state, comm=comm, unroll=unroll)
                  if n_mega else None)
            tail_pp = (mpx.compile(one_step, state, comm=comm, unroll=tail)
                       if tail else None)

            def runner(s, total, _pp=pp, _tail=tail_pp, _n=n_mega):
                assert total == n_steps - 1, \
                    "megastep runner compiled for a fixed step count"
                s = fused(s, 0)
                for _ in range(_n):
                    s = _pp(s)
                if _tail is not None:
                    s = _tail(s)
                return s

            megastep_ok = True
            if info is not None:
                info["unroll"] = unroll
        except Exception as e:  # noqa: BLE001 - diagnostic fallback
            print(f"shallow_water: megastep unroll unavailable ({e!r}); "
                  "falling back to the whole-run program", file=sys.stderr)
    if pinned and not megastep_ok:
        # AOT-pin the whole-run program (docs/aot.md): the timed calls
        # then execute a compiled artifact with zero per-call key work —
        # the dispatch_overhead_s line item bench.py reports is exactly
        # what this removes.  The step-count static folds at pin time.
        # Best-effort: any pin failure falls back to the spmd program
        # so the benchmark never regresses.  (With an ACTIVE megastep
        # runner this pin is skipped: nothing would execute it.)
        try:
            pp = mpx.compile(fused, state, n_steps - 1)

            def runner(s, total, _pp=pp, _total=n_steps - 1):
                assert total == _total, "pinned for a fixed step count"
                return _pp(s)
        except Exception as e:  # noqa: BLE001 - diagnostic fallback
            print(f"shallow_water: AOT pinning unavailable ({e!r}); "
                  "falling back to the spmd program", file=sys.stderr)
    # sync points fetch ONE element: on remote-attached devices a full-array
    # fetch costs seconds of tunnel transfer and would pollute the timing
    # (block_until_ready alone is not a reliable sync there).  Best-of-2
    # timed runs: the tunnel adds run-to-run jitter that a single sample
    # conflates with the program's own speed.
    np.asarray(runner(state, n_steps - 1).h[0, 0, 0])  # compile + warm-up
    wall = float("inf")
    for _ in range(2):
        start = time.perf_counter()
        out = runner(state, n_steps - 1)
        np.asarray(out.h[0, 0, 0])  # device->host sync
        wall = min(wall, time.perf_counter() - start)
    if return_state:
        return wall, n_steps, out
    return wall, n_steps


def save_animation(snapshots, cfg: Config, path: str = "shallow-water.gif"):
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib import animation
    except ImportError:
        print("matplotlib not available; skipping animation")
        return
    fig, ax = plt.subplots(figsize=(8, 4))
    frames = [reassemble(s, cfg) - cfg.depth for s in snapshots]
    vmax = np.abs(frames[-1]).max()
    im = ax.imshow(frames[0], origin="lower", cmap="RdBu_r", vmin=-vmax, vmax=vmax)
    fig.colorbar(im, label="height anomaly [m]")

    def update(i):
        im.set_data(frames[i])
        ax.set_title(f"step {i}")
        return (im,)

    anim = animation.FuncAnimation(fig, update, frames=len(frames), interval=50)
    anim.save(path, writer=animation.PillowWriter(fps=20))
    print(f"wrote {path}")


def pick_process_grid(n: int):
    """Same decomposition rule as the reference: nproc_y = min(n, 2), and
    even device counts only above 1 (ref examples/shallow_water.py:57-64
    validates against its supported process counts the same way)."""
    nproc_y = min(n, 2)
    if n % nproc_y != 0:
        raise ValueError(
            f"Got invalid number of devices: {n}. Use 1 or an even count "
            "(the domain is decomposed over a (2, n//2) grid)."
        )
    return nproc_y, n // nproc_y


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--benchmark", action="store_true",
                   help="reference benchmark config: 100x domain, 0.1 days, "
                        "no output (ref docs/shallow-water.rst:44-55)")
    p.add_argument("--t1-days", type=float, default=None,
                   help="simulated model days (default: 1.0; benchmark: 0.1)")
    p.add_argument("--scale", type=float, default=None,
                   help="linear domain scale factor (benchmark default: 10)")
    p.add_argument("--save-animation", action="store_true")
    p.add_argument("--n-devices", type=int, default=None,
                   help="use the first N local devices (default: all)")
    args = p.parse_args()

    devices = jax.devices()
    if args.n_devices:
        devices = devices[: args.n_devices]
    nproc_y, nproc_x = pick_process_grid(len(devices))

    scale = args.scale if args.scale is not None else (10.0 if args.benchmark else 1.0)
    cfg = Config(nproc_y=nproc_y, nproc_x=nproc_x)
    cfg = replace(cfg, nx=int(cfg.nx * scale), ny=int(cfg.ny * scale))
    t1 = (args.t1_days if args.t1_days is not None
          else (0.1 if args.benchmark else 1.0)) * DAY_IN_SECONDS

    print(f"shallow water: {cfg.ny}x{cfg.nx} interior on a "
          f"({nproc_y}, {nproc_x}) mesh of {len(devices)} "
          f"{devices[0].platform.upper()} device(s), dt={cfg.dt:.1f}s")

    if args.benchmark:
        # one fused XLA program for the whole run (no snapshots)
        wall, n_steps = solve_fused(cfg, t1, devices=devices, fast="auto")
        snapshots = []
    else:
        snapshots, wall, n_steps = solve(cfg, t1, devices=devices,
                                         verbose=True, fast="auto")
    print(f"\nSolution took {wall:.2f}s "
          f"({n_steps} steps, {n_steps / wall:.1f} steps/s)")

    if args.save_animation and snapshots:
        save_animation(snapshots, cfg)


if __name__ == "__main__":
    main()
