"""Runtime telemetry walkthrough (docs/observability.md).

Runs a small collective workload under the ``events`` telemetry tier,
prints the cross-rank ``report()`` table (per-op calls/bytes, latency
percentiles, the skew/straggler columns), and leaves per-process JSONL
journals ready for the merge CLI::

    MPI4JAX_TPU_TELEMETRY_DIR=/tmp/mpx-tel python examples/telemetry_demo.py
    python -m mpi4jax_tpu.telemetry merge /tmp/mpx-tel --perfetto trace.json

(The CI telemetry lane runs exactly this pipeline on the 8-device CPU
mesh and uploads the merged trace as an artifact.)

Run: python examples/telemetry_demo.py
"""

import os
import sys
import tempfile

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402


def main():
    if not os.environ.get("MPI4JAX_TPU_TELEMETRY_DIR"):
        os.environ["MPI4JAX_TPU_TELEMETRY_DIR"] = tempfile.mkdtemp(
            prefix="mpx-telemetry-"
        )
    mpx.set_telemetry_mode("events")

    comm = mpx.get_default_comm()
    size = comm.Get_size()

    @mpx.spmd
    def step(x):
        # a reduction (algorithm-selected), a broadcast, and a ring hop:
        # three distinct rows in the report table
        s, tok = mpx.allreduce(x, op=mpx.SUM)
        b, tok = mpx.bcast(mpx.varying(s), 0, token=tok)
        r, _ = mpx.sendrecv(b, b, dest=mpx.shift(1), token=tok)
        return r

    x = jnp.ones((size, 1024), jnp.float32)
    for _ in range(5):
        out = step(x)
    jax.block_until_ready(out)

    print(f"journal dir: {os.environ['MPI4JAX_TPU_TELEMETRY_DIR']}")
    mpx.telemetry.report()
    mpx.set_telemetry_mode(None)


if __name__ == "__main__":
    main()
