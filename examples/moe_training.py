"""Expert-parallel MoE training on the alltoall fast path (docs/moe.md).

The workload class ROADMAP item 5a names: ``k`` ranks each own one
expert, a seeded top-1 gate routes tokens, and the two hottest
collectives are alltoalls — capacity-bucketed **dispatch**, per-expert
MLP, then the **combine** exchange issued via ``mpx.alltoall_start`` so
each capacity chunk's combine overlaps the next chunk's expert compute
(``MPI4JAX_TPU_MOE_CAPACITY_CHUNKS``, ops/_async.py).

Three stages, mirroring examples/hierarchical_demo.py:

1. **pin** — the overlapped pipeline must produce BIT-IDENTICAL output
   to the synchronous layer (``chunks=1``): the async split is pure
   routing, so this is an equality, not a tolerance;
2. **train** — a few SGD steps through the synchronous layer (gate +
   dispatch/combine are differentiable; dropped tokens contribute zero
   gradient), printing the decreasing loss;
3. **telemetry** — counters-tier per-link-class byte split of the
   alltoall traffic: under ``MPI4JAX_TPU_TOPOLOGY=2x4`` (the CI moe
   lane fakes a 2-host pod this way) the dispatch/combine exchanges
   land modeled bytes on BOTH the ``intra_host`` and ``inter_host``
   classes once the payload clears
   ``MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES``.

Verified clean by the trace-time verifier in CI (the analyze lane runs
``python -m mpi4jax_tpu.analysis --ranks 8 --cost`` over every example);
the rank-divergent capacity twin that FAILS verification lives at
examples/broken/moe_divergent_capacity.py.
"""

import argparse
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import mpi4jax_tpu as mpx  # noqa: E402
from mpi4jax_tpu.parallel import moe  # noqa: E402

TOKENS = 32
D = 16
D_FF = 32
SEED = 7


def build_inputs(n):
    rng = np.random.default_rng(SEED)
    x = rng.standard_normal((n, TOKENS, D)).astype(np.float32)
    tgt = rng.standard_normal((n, TOKENS, D)).astype(np.float32) * 0.1
    params = [moe.init_moe_params(D, D_FF, n, rank=r, seed=SEED)
              for r in range(n)]
    w_gate = np.stack([p.w_gate for p in params])  # replicated router
    w_in = np.stack([p.w_in for p in params])      # rank r = expert r
    w_out = np.stack([p.w_out for p in params])
    return (jnp.asarray(x), jnp.asarray(tgt), jnp.asarray(w_gate),
            jnp.asarray(w_in), jnp.asarray(w_out))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    mesh = mpx.make_world_mesh(devices=jax.devices())
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()
    x, tgt, w_gate, w_in, w_out = build_inputs(n)

    # --- 1. the pin: overlapped pipeline == synchronous layer, bitwise
    def fwd(chunks):
        @mpx.spmd(comm=comm)
        def prog(xv, wg, wi, wo):
            y, _ = moe.moe_layer(xv, moe.MoEParams(wg, wi, wo), comm=comm,
                                 chunks=chunks)
            return mpx.varying(y)

        return np.asarray(prog(x, w_gate, w_in, w_out))

    y_sync = fwd(1)
    y_ovl = fwd(2)
    np.testing.assert_array_equal(y_sync, y_ovl)
    cap = moe.capacity_for(TOKENS, n)
    print(f"pin: overlapped combine (2 capacity chunks) bit-identical to "
          f"the synchronous layer ({n} experts, capacity {cap})")

    # --- 2. train: a few SGD steps through the differentiable layer
    @mpx.spmd(comm=comm)
    def train_step(xv, tv, wg, wi, wo):
        def loss_fn(wg_, wi_, wo_):
            y, _ = moe.moe_layer(xv, moe.MoEParams(wg_, wi_, wo_),
                                 comm=comm, chunks=1)
            return jnp.mean((y - tv) ** 2)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            wg, wi, wo)
        # the router is replicated: average its gradient; expert weights
        # are rank-local, their gradients stay local
        g_gate, tok = mpx.allreduce(grads[0], op=mpx.SUM)
        loss_g, _ = mpx.allreduce(loss, token=tok)
        return (mpx.varying(loss_g * (1.0 / n)),
                mpx.varying(wg - args.lr * g_gate * (1.0 / n)),
                mpx.varying(wi - args.lr * grads[1]),
                mpx.varying(wo - args.lr * grads[2]))

    losses = []
    for _ in range(args.steps):
        loss, w_gate, w_in, w_out = train_step(x, tgt, w_gate, w_in, w_out)
        losses.append(float(np.asarray(loss)[0]))
    print("train: losses " + " -> ".join(f"{v:.5f}" for v in losses))
    assert losses[-1] < losses[0], losses

    # --- 3. telemetry: where the token traffic lands per link class
    mpx.set_telemetry_mode("counters")
    try:
        fwd(2)
        rows = [r for r in mpx.telemetry.snapshot()["ops"].values()
                if r["op"].startswith("alltoall")]
        for row in rows:
            print(f"telemetry: {row['op']} algo={row['algo']} "
                  f"intra_host={row['intra_bytes']} B "
                  f"inter_host={row['inter_bytes']} B")
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()

    if args.json:
        import json

        print(json.dumps({"losses": losses, "experts": n,
                          "capacity": cap}))


if __name__ == "__main__":
    main()
